"""Spatial / vision op kernels: N-d conv & pooling, grid sampling, ROI ops.

Reference surface: python/paddle/nn/functional/conv.py (conv3d at
nn/layer/conv.py:899), pooling.py (1d/3d + adaptive variants),
vision.py (grid_sample, affine_grid, pixel_unshuffle, channel_shuffle),
paddle.vision.ops (roi_align, roi_pool, deform_conv2d, nms), and the phi
kernels grid_sample_kernel.cu / roi_align_kernel.cu / deformable_conv_kernel.
TPU design: everything lowers to lax.conv_general_dilated /
lax.reduce_window / gather compositions that XLA tiles onto the MXU — no
per-op CUDA. All ops are differentiable through jax's vjp.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
import numpy as _np

from .nn_ops import avg_pool2d, max_pool2d  # re-used by adaptive wrappers


def _ntuple(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(v)
        if len(v) == 1:
            return tuple(v) * n
        raise ValueError(f"expected {n}-tuple, got {v}")
    return (v,) * n


# ----------------------------------------------------------------- conv N-d
_CONV_FMT = {1: ("NCL", "OIL"), 2: ("NCHW", "OIHW"), 3: ("NCDHW", "OIDHW")}


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, nd,
             channel_last=False):
    stride = _ntuple(stride, nd)
    dilation = _ntuple(dilation, nd)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _ntuple(padding, nd) if not (
            isinstance(padding, (list, tuple)) and len(padding) == 2 * nd
        ) else padding
        if len(p) == nd:
            pad = [(pi, pi) for pi in p]
        else:  # [before0, after0, before1, after1, ...]
            pad = [(p[2 * i], p[2 * i + 1]) for i in range(nd)]
    lhs_fmt, rhs_fmt = _CONV_FMT[nd]
    if channel_last:
        lhs_fmt = "N" + lhs_fmt[2:] + "C"
    dn = lax.conv_dimension_numbers(x.shape, weight.shape,
                                    (lhs_fmt, rhs_fmt, lhs_fmt))
    out = lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=jnp.float32 if x.dtype == jnp.float32 else None,
    )
    if bias is not None:
        shape = [1, -1] + [1] * nd if not channel_last else [1] + [1] * nd + [-1]
        out = out + bias.reshape(shape)
    return out


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3,
                    channel_last=(data_format == "NDHWC"))


def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       dilation, groups, nd):
    stride = _ntuple(stride, nd)
    dilation = _ntuple(dilation, nd)
    p = _ntuple(padding, nd)
    op = _ntuple(output_padding, nd)
    ks = weight.shape[2:]
    pad = [
        (dilation[i] * (ks[i] - 1) - p[i],
         dilation[i] * (ks[i] - 1) - p[i] + op[i])
        for i in range(nd)
    ]
    # weight layout paddle: [in, out//groups, *ks] -> flip + swap to OI*ks
    w = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))
    w = jnp.swapaxes(w, 0, 1)
    if groups > 1:
        w = jnp.concatenate(jnp.split(w, groups, axis=1), axis=0)
    lhs_fmt, rhs_fmt = _CONV_FMT[nd]
    dn = lax.conv_dimension_numbers(x.shape, w.shape, (lhs_fmt, rhs_fmt, lhs_fmt))
    out = lax.conv_general_dilated(
        x, w, window_strides=(1,) * nd, padding=pad, lhs_dilation=stride,
        rhs_dilation=dilation, dimension_numbers=dn, feature_group_count=groups,
    )
    if bias is not None:
        out = out + bias.reshape([1, -1] + [1] * nd)
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, data_format="NCL"):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 1)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCDHW"):
    return _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                              dilation, groups, 3)


# ----------------------------------------------------------------- pool N-d
def _pool_nd(x, kernel_size, stride, padding, nd, reducer, init, ceil_mode):
    k = _ntuple(kernel_size, nd)
    s = _ntuple(stride, nd) if stride is not None else k
    p = _ntuple(padding, nd)
    window = (1, 1) + k
    strides = (1, 1) + s
    pads = [(0, 0), (0, 0)] + [(pi, pi) for pi in p]
    if ceil_mode:
        from .nn_ops import _ceil_hi_pad

        for i in range(nd):
            pads[2 + i] = (p[i], p[i] + _ceil_hi_pad(x.shape[2 + i], k[i],
                                                     s[i], p[i]))
    return lax.reduce_window(x, init, reducer, window, strides, pads)


def _neg_init(x):
    return -jnp.inf if jnp.issubdtype(x.dtype, jnp.inexact) else jnp.iinfo(x.dtype).min


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False):
    k = _ntuple(kernel_size, 1)
    s = _ntuple(stride, 1) if stride is not None else k
    p = _ntuple(padding, 1)
    neg = _neg_init(x)
    if return_mask:
        out, idx = _max_pool_with_mask(x[..., None], (k[0], 1), (s[0], 1),
                                       (p[0], 0), ceil_mode=ceil_mode)
        return out[..., 0], idx[..., 0]
    pads = [(0, 0), (0, 0), (p[0], p[0])]
    if ceil_mode:
        from .nn_ops import _ceil_hi_pad

        pads[2] = (p[0], p[0] + _ceil_hi_pad(x.shape[2], k[0], s[0], p[0]))
    return lax.reduce_window(x, neg, lax.max, (1, 1, k[0]), (1, 1, s[0]), pads)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False):
    k = _ntuple(kernel_size, 1)
    s = _ntuple(stride, 1) if stride is not None else k
    p = _ntuple(padding, 1)
    summed = _pool_nd(x[:, :, :, None], (k[0], 1), (s[0], 1), (p[0], 0), 2,
                      lax.add, _np.zeros((), x.dtype), ceil_mode)[..., 0]
    if exclusive and (p[0] or ceil_mode):
        counts = _pool_nd(jnp.ones_like(x)[:, :, :, None], (k[0], 1), (s[0], 1),
                          (p[0], 0), 2, lax.add, _np.zeros((), x.dtype),
                          ceil_mode)[..., 0]
        return summed / counts
    return summed / k[0]


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW"):
    if return_mask:
        return _max_pool_with_mask_nd(x, kernel_size, stride, padding, 3,
                                      ceil_mode=ceil_mode)
    return _pool_nd(x, kernel_size, stride, padding, 3, lax.max, _neg_init(x),
                    ceil_mode)


def avg_pool3d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCDHW"):
    k = _ntuple(kernel_size, 3)
    p = _ntuple(padding, 3)
    summed = _pool_nd(x, kernel_size, stride, padding, 3, lax.add,
                      _np.zeros((), x.dtype), ceil_mode)
    if exclusive and (any(p) or ceil_mode):
        counts = _pool_nd(jnp.ones_like(x), kernel_size, stride, padding, 3,
                          lax.add, _np.zeros((), x.dtype), ceil_mode)
        return summed / counts
    return summed / (k[0] * k[1] * k[2])


def _max_pool_with_mask(x, k, s, p, ceil_mode=False):
    """max_pool2d returning (out, flat-index mask) like the reference
    (mask = argmax position in the flattened input H*W, phi max_pool2d_with_index).

    Padding is applied explicitly with the dtype minimum
    (conv_general_dilated_patches zero-pads, and a 0 pad slot would win the
    max over negative inputs and yield an out-of-range index). The flat index
    is reconstructed from the within-window argmax in INTEGER arithmetic
    (row = oy*s - p + am//kw ...) — no float index map, so it is exact for
    any H*W (a float32 map breaks above 2^24)."""
    n, c, h, w = x.shape
    neg = (_np.finfo(x.dtype).min if jnp.issubdtype(x.dtype, jnp.inexact)
           else _np.iinfo(x.dtype).min)
    hi = [p[0], p[1]]
    if ceil_mode:
        from .nn_ops import _ceil_hi_pad

        for i, dim in enumerate((h, w)):
            hi[i] += _ceil_hi_pad(dim, k[i], s[i], p[i])
    xp = jnp.pad(x, ((0, 0), (0, 0), (p[0], hi[0]), (p[1], hi[1])),
                 constant_values=neg)
    patches = lax.conv_general_dilated_patches(
        xp, filter_shape=k, window_strides=s, padding=[(0, 0), (0, 0)],
        dimension_numbers=lax.conv_dimension_numbers(
            xp.shape, (1, c, *k), ("NCHW", "OIHW", "NCHW")),
    )  # [n, c*kh*kw, oh, ow]
    oh, ow = patches.shape[2], patches.shape[3]
    patches = patches.reshape(n, c, k[0] * k[1], oh, ow)
    am = jnp.argmax(patches, axis=2)
    out = jnp.max(patches, axis=2)
    row = jnp.arange(oh, dtype=jnp.int32)[None, None, :, None] * s[0] - p[0] + (am // k[1]).astype(jnp.int32)
    col = jnp.arange(ow, dtype=jnp.int32)[None, None, None, :] * s[1] - p[1] + (am % k[1]).astype(jnp.int32)
    row = jnp.clip(row, 0, h - 1)  # all-padding windows argmax to a pad slot
    col = jnp.clip(col, 0, w - 1)
    idx = row.astype(jnp.int64) * w + col.astype(jnp.int64)
    return out, idx


def _max_pool_with_mask_nd(x, kernel_size, stride, padding, nd, ceil_mode=False):
    if nd == 3:
        # fold depth into batch and pool 2-d per depth slice is wrong for
        # kd > 1; use the generic patch route via reshape to 2-d when kd == 1
        k = _ntuple(kernel_size, 3)
        if k[0] == 1:
            n, c, d, h, w = x.shape
            s = _ntuple(stride, 3) if stride is not None else k
            p = _ntuple(padding, 3)
            out, idx = _max_pool_with_mask(
                x.reshape(n, c * d, h, w), (k[1], k[2]), (s[1], s[2]),
                (p[1], p[2]), ceil_mode=ceil_mode)
            oh, ow = out.shape[-2:]
            return (out.reshape(n, c, d, oh, ow), idx.reshape(n, c, d, oh, ow))
        raise NotImplementedError("max_pool3d return_mask requires kd == 1")
    raise NotImplementedError


def max_pool2d_with_mask(x, kernel_size, stride=None, padding=0, ceil_mode=False):
    k = _ntuple(kernel_size, 2)
    s = _ntuple(stride, 2) if stride is not None else k
    p = _ntuple(padding, 2)
    return _max_pool_with_mask(x, k, s, p, ceil_mode=ceil_mode)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW"):
    """Scatter pooled values back to the argmax positions (phi max_unpool2d)."""
    k = _ntuple(kernel_size, 2)
    s = _ntuple(stride, 2) if stride is not None else k
    p = _ntuple(padding, 2)
    n, c, oh, ow = x.shape
    if output_size is None:
        h = (oh - 1) * s[0] - 2 * p[0] + k[0]
        w = (ow - 1) * s[1] - 2 * p[1] + k[1]
    else:
        h, w = output_size[-2:]
    flat = jnp.zeros((n, c, h * w), x.dtype)
    idx = indices.reshape(n, c, oh * ow).astype(jnp.int32)
    vals = x.reshape(n, c, oh * ow)
    out = jax.vmap(jax.vmap(lambda f, i, v: f.at[i].set(v)))(flat, idx, vals)
    return out.reshape(n, c, h, w)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None):
    out = max_unpool2d(x[..., None], indices[..., None],
                       (_ntuple(kernel_size, 1)[0], 1),
                       (_ntuple(stride, 1)[0], 1) if stride is not None else None,
                       (_ntuple(padding, 1)[0], 0),
                       output_size=None if output_size is None
                       else (*tuple(output_size), 1))
    return out[..., 0]


def adaptive_avg_pool1d(x, output_size):
    from .nn_ops import _adaptive_pool_general

    out = _ntuple(output_size, 1)[0]
    l = x.shape[2]
    if l % out == 0:
        k = l // out
        return avg_pool1d(x, k, stride=k)
    x4 = x[:, :, :, None]
    return _adaptive_pool_general(x4, out, 1, (2, 3))[..., 0]


def adaptive_max_pool1d(x, output_size):
    from .nn_ops import _adaptive_pool_general

    out = _ntuple(output_size, 1)[0]
    l = x.shape[2]
    if l % out == 0:
        k = l // out
        return max_pool1d(x, k, stride=k)
    x4 = x[:, :, :, None]
    return _adaptive_pool_general(x4, out, 1, (2, 3), reducer=jnp.max)[..., 0]


def _adaptive_pool3d(x, output_size, reducer):
    import numpy as np

    od, oh, ow = _ntuple(output_size, 3)
    n, c, d, h, w = x.shape
    if d % od == 0 and h % oh == 0 and w % ow == 0:
        k = (d // od, h // oh, w // ow)
        if reducer is jnp.mean:
            return avg_pool3d(x, k, stride=k)
        return max_pool3d(x, k, stride=k)
    cells = []
    for i in range(od):
        sl_d = slice(int(np.floor(i * d / od)), int(np.ceil((i + 1) * d / od)))
        rows = []
        for j in range(oh):
            sl_h = slice(int(np.floor(j * h / oh)), int(np.ceil((j + 1) * h / oh)))
            cols = []
            for m in range(ow):
                sl_w = slice(int(np.floor(m * w / ow)), int(np.ceil((m + 1) * w / ow)))
                cols.append(reducer(x[:, :, sl_d, sl_h, sl_w], axis=(2, 3, 4),
                                    keepdims=True))
            rows.append(jnp.concatenate(cols, axis=4))
        cells.append(jnp.concatenate(rows, axis=3))
    return jnp.concatenate(cells, axis=2)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    return _adaptive_pool3d(x, output_size, jnp.mean)


def adaptive_max_pool3d(x, output_size, data_format="NCDHW"):
    return _adaptive_pool3d(x, output_size, jnp.max)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW"):
    k = _ntuple(kernel_size, 2)
    p = float(norm_type)
    powed = jnp.abs(x) ** p
    summed = _pool_nd(powed, kernel_size, stride, padding, 2, lax.add,
                      _np.zeros((), x.dtype), ceil_mode)
    return summed ** (1.0 / p)


# ------------------------------------------------------------ grid sampling
def _unnormalize(coord, size, align_corners):
    if align_corners:
        return (coord + 1.0) * (size - 1) / 2.0
    return ((coord + 1.0) * size - 1.0) / 2.0


def _reflect(x, lo, hi):
    # reflect into [lo, hi] (float bounds), standard double-mirror
    rng = hi - lo
    if rng <= 0:
        return jnp.zeros_like(x)
    x = jnp.abs(x - lo) % (2 * rng)
    return lo + jnp.where(x > rng, 2 * rng - x, x)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True):
    """x: [N, C, H, W]; grid: [N, Hg, Wg, 2] with (x, y) in [-1, 1].

    Reference: phi/kernels/gpu/grid_sample_kernel.cu. Gather-based bilinear
    with zeros/border/reflection handling; nearest supported.
    """
    n, c, h, w = x.shape
    gx = _unnormalize(grid[..., 0].astype(jnp.float32), w, align_corners)
    gy = _unnormalize(grid[..., 1].astype(jnp.float32), h, align_corners)

    if padding_mode == "reflection":
        if align_corners:
            gx = _reflect(gx, 0.0, w - 1.0)
            gy = _reflect(gy, 0.0, h - 1.0)
        else:
            gx = _reflect(gx, -0.5, w - 0.5)
            gy = _reflect(gy, -0.5, h - 0.5)
        gx = jnp.clip(gx, 0, w - 1)
        gy = jnp.clip(gy, 0, h - 1)
    elif padding_mode == "border":
        gx = jnp.clip(gx, 0, w - 1)
        gy = jnp.clip(gy, 0, h - 1)

    def gather(ix, iy, valid):
        # ix/iy int32 [N, Hg, Wg]; returns [N, C, Hg, Wg]
        ixc = jnp.clip(ix, 0, w - 1)
        iyc = jnp.clip(iy, 0, h - 1)
        flat = x.reshape(n, c, h * w)
        lin = (iyc * w + ixc).reshape(n, -1)  # [N, Hg*Wg]
        out = jnp.take_along_axis(flat, lin[:, None, :], axis=2)
        out = out.reshape(n, c, *ix.shape[1:])
        return out * valid[:, None].astype(x.dtype)

    def in_bounds(ix, iy):
        if padding_mode == "zeros":
            return ((ix >= 0) & (ix <= w - 1) & (iy >= 0) & (iy <= h - 1))
        return jnp.ones_like(ix, dtype=bool)

    if mode == "nearest":
        ix = jnp.round(gx).astype(jnp.int32)
        iy = jnp.round(gy).astype(jnp.int32)
        return gather(ix, iy, in_bounds(ix, iy))

    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    x1, y1 = x0 + 1, y0 + 1
    wx1 = (gx - x0).astype(x.dtype)
    wy1 = (gy - y0).astype(x.dtype)
    wx0, wy0 = 1 - wx1, 1 - wy1
    x0i, x1i = x0.astype(jnp.int32), x1.astype(jnp.int32)
    y0i, y1i = y0.astype(jnp.int32), y1.astype(jnp.int32)
    out = (
        gather(x0i, y0i, in_bounds(x0i, y0i)) * (wx0 * wy0)[:, None]
        + gather(x1i, y0i, in_bounds(x1i, y0i)) * (wx1 * wy0)[:, None]
        + gather(x0i, y1i, in_bounds(x0i, y1i)) * (wx0 * wy1)[:, None]
        + gather(x1i, y1i, in_bounds(x1i, y1i)) * (wx1 * wy1)[:, None]
    )
    return out


def affine_grid(theta, out_shape, align_corners=True):
    """theta: [N, 2, 3] -> grid [N, H, W, 2] (4-len out_shape), or
    [N, 3, 4] -> [N, D, H, W, 3] (5-len). Reference: phi affine_grid."""
    out_shape = [int(s) for s in out_shape]

    def base(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size)
        step = 2.0 / size
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

    if len(out_shape) == 4:
        n, _, h, w = out_shape
        ys, xs = jnp.meshgrid(base(h), base(w), indexing="ij")
        ones = jnp.ones_like(xs)
        coords = jnp.stack([xs, ys, ones], axis=-1)  # [H, W, 3]
        grid = jnp.einsum("hwk,njk->nhwj", coords, theta.astype(jnp.float32))
        return grid  # [N, H, W, 2]
    n, _, d, h, w = out_shape
    zs, ys, xs = jnp.meshgrid(base(d), base(h), base(w), indexing="ij")
    ones = jnp.ones_like(xs)
    coords = jnp.stack([xs, ys, zs, ones], axis=-1)
    return jnp.einsum("dhwk,njk->ndhwj", coords, theta.astype(jnp.float32))


# ---------------------------------------------------------------- ROI ops
def roi_align(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """x: [N, C, H, W]; boxes: [R, 4] (x1, y1, x2, y2); boxes_num: [N].

    Reference: phi/kernels/gpu/roi_align_kernel.cu. sampling_ratio=-1 (the
    reference's adaptive bin sampling) is approximated with a fixed 2x2
    sample grid per bin — adaptive counts are data-dependent, which cannot
    be staged into one XLA program.
    """
    ph, pw = _ntuple(output_size, 2)
    sr = 2 if sampling_ratio <= 0 else sampling_ratio
    n, c, h, w = x.shape
    r = boxes.shape[0]
    if boxes_num is None:
        batch_idx = jnp.zeros((r,), jnp.int32)
    else:
        batch_idx = jnp.searchsorted(
            jnp.cumsum(jnp.asarray(boxes_num)), jnp.arange(r), side="right"
        ).astype(jnp.int32)

    offset = 0.5 if aligned else 0.0
    boxes = boxes.astype(jnp.float32) * spatial_scale - offset
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    roi_w = x2 - x1 if aligned else jnp.maximum(x2 - x1, 1.0)
    roi_h = y2 - y1 if aligned else jnp.maximum(y2 - y1, 1.0)
    bin_w = roi_w / pw
    bin_h = roi_h / ph

    # sample coordinates: [R, ph*sr] x [R, pw*sr]
    iy = jnp.arange(ph * sr)
    ix = jnp.arange(pw * sr)
    sy = y1[:, None] + (iy[None, :] // sr) * bin_h[:, None] + \
        ((iy[None, :] % sr) + 0.5) / sr * bin_h[:, None]
    sx = x1[:, None] + (ix[None, :] // sr) * bin_w[:, None] + \
        ((ix[None, :] % sr) + 0.5) / sr * bin_w[:, None]

    def sample_one(xi, syi, sxi):
        # xi: [C, H, W]; syi: [ph*sr]; sxi: [pw*sr] -> [C, ph, pw]
        gy = jnp.broadcast_to(syi[:, None], (ph * sr, pw * sr))
        gx = jnp.broadcast_to(sxi[None, :], (ph * sr, pw * sr))
        x0 = jnp.floor(gx)
        y0 = jnp.floor(gy)
        wx1 = gx - x0
        wy1 = gy - y0

        def g(ixg, iyg):
            v = ((ixg >= 0) & (ixg <= w - 1) & (iyg >= 0) & (iyg <= h - 1))
            ixc = jnp.clip(ixg, 0, w - 1)
            iyc = jnp.clip(iyg, 0, h - 1)
            flat = xi.reshape(c, h * w)
            lin = (iyc * w + ixc).reshape(-1)
            out = jnp.take(flat, lin, axis=1).reshape(c, ph * sr, pw * sr)
            return out * v.astype(xi.dtype)

        x0i, y0i = x0.astype(jnp.int32), y0.astype(jnp.int32)
        val = (g(x0i, y0i) * ((1 - wx1) * (1 - wy1))
               + g(x0i + 1, y0i) * (wx1 * (1 - wy1))
               + g(x0i, y0i + 1) * ((1 - wx1) * wy1)
               + g(x0i + 1, y0i + 1) * (wx1 * wy1))
        return jnp.mean(val.reshape(c, ph, sr, pw, sr), axis=(2, 4))

    feats = x[batch_idx]  # [R, C, H, W]
    return jax.vmap(sample_one)(feats, sy, sx)


def roi_pool(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0):
    """Max-pool ROI features (phi roi_pool_kernel). Same sampled-grid
    approximation as roi_align but with a max reduction."""
    ph, pw = _ntuple(output_size, 2)
    sr = 2
    n, c, h, w = x.shape
    r = boxes.shape[0]
    if boxes_num is None:
        batch_idx = jnp.zeros((r,), jnp.int32)
    else:
        batch_idx = jnp.searchsorted(
            jnp.cumsum(jnp.asarray(boxes_num)), jnp.arange(r), side="right"
        ).astype(jnp.int32)
    boxes = jnp.round(boxes.astype(jnp.float32) * spatial_scale)
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    roi_w = jnp.maximum(x2 - x1 + 1, 1.0)
    roi_h = jnp.maximum(y2 - y1 + 1, 1.0)
    iy = jnp.arange(ph * sr)
    ix = jnp.arange(pw * sr)
    sy = y1[:, None] + (iy[None, :] + 0.5) / (ph * sr) * roi_h[:, None]
    sx = x1[:, None] + (ix[None, :] + 0.5) / (pw * sr) * roi_w[:, None]

    def sample_one(xi, syi, sxi):
        iyg = jnp.clip(syi.astype(jnp.int32), 0, h - 1)
        ixg = jnp.clip(sxi.astype(jnp.int32), 0, w - 1)
        grid_y = jnp.broadcast_to(iyg[:, None], (ph * sr, pw * sr))
        grid_x = jnp.broadcast_to(ixg[None, :], (ph * sr, pw * sr))
        flat = xi.reshape(c, h * w)
        lin = (grid_y * w + grid_x).reshape(-1)
        vals = jnp.take(flat, lin, axis=1).reshape(c, ph * sr, pw * sr)
        return jnp.max(vals.reshape(c, ph, sr, pw, sr), axis=(2, 4))

    return jax.vmap(sample_one)(x[batch_idx], sy, sx)


# ------------------------------------------------------- deformable conv
def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None):
    """Deformable conv v1/v2 (phi deformable_conv_kernel). Bilinear-samples
    the input at offset-shifted taps, then a dense matmul with the weights —
    the gather/matmul split keeps the FLOPs on the MXU."""
    s = _ntuple(stride, 2)
    p = _ntuple(padding, 2)
    d = _ntuple(dilation, 2)
    n, c, h, w = x.shape
    oc, ic_g, kh, kw = weight.shape
    oh = (h + 2 * p[0] - d[0] * (kh - 1) - 1) // s[0] + 1
    ow = (w + 2 * p[1] - d[1] * (kw - 1) - 1) // s[1] + 1
    # offset: [N, 2*dg*kh*kw, oh, ow] (y, x interleaved pairs, reference order)
    off = offset.reshape(n, deformable_groups, kh * kw, 2, oh, ow)

    base_y = (jnp.arange(oh) * s[0] - p[0])[:, None]  # [oh, 1]
    base_x = (jnp.arange(ow) * s[1] - p[1])[None, :]  # [1, ow]
    taps_y = jnp.repeat(jnp.arange(kh) * d[0], kw)     # [kh*kw]
    taps_x = jnp.tile(jnp.arange(kw) * d[1], kh)       # [kh*kw]
    ty = base_y[None] + taps_y[:, None, None]          # [kh*kw, oh, ow]
    tx = base_x[None] + taps_x[:, None, None]

    sy = ty[None, None] + off[:, :, :, 0]              # [N, dg, kh*kw, oh, ow]
    sx = tx[None, None] + off[:, :, :, 1]

    cg = c // deformable_groups

    def bilinear(img, gy, gx):
        # img: [cg, h, w]; gy/gx: [kh*kw, oh, ow] -> [cg, kh*kw, oh, ow]
        x0 = jnp.floor(gx)
        y0 = jnp.floor(gy)
        wx1 = (gx - x0).astype(img.dtype)
        wy1 = (gy - y0).astype(img.dtype)

        def g(ixg, iyg):
            v = ((ixg >= 0) & (ixg <= w - 1) & (iyg >= 0) & (iyg <= h - 1))
            ixc = jnp.clip(ixg, 0, w - 1)
            iyc = jnp.clip(iyg, 0, h - 1)
            lin = (iyc * w + ixc).reshape(-1)
            out = jnp.take(img.reshape(cg, h * w), lin, axis=1)
            return out.reshape(cg, *gy.shape) * v.astype(img.dtype)

        x0i, y0i = x0.astype(jnp.int32), y0.astype(jnp.int32)
        return (g(x0i, y0i) * ((1 - wx1) * (1 - wy1))
                + g(x0i + 1, y0i) * (wx1 * (1 - wy1))
                + g(x0i, y0i + 1) * ((1 - wx1) * wy1)
                + g(x0i + 1, y0i + 1) * (wx1 * wy1))

    # [N, dg, cg, kh*kw, oh, ow]
    cols = jax.vmap(  # over batch
        jax.vmap(bilinear)  # over deformable groups
    )(x.reshape(n, deformable_groups, cg, h, w), sy, sx)
    if mask is not None:  # v2 modulation: [N, dg*kh*kw, oh, ow]
        m = mask.reshape(n, deformable_groups, 1, kh * kw, oh, ow)
        cols = cols * m.astype(cols.dtype)
    cols = cols.reshape(n, c * kh * kw, oh * ow)
    wmat = weight.reshape(groups, oc // groups, ic_g * kh * kw)
    cols = cols.reshape(n, groups, ic_g * kh * kw, oh * ow)
    out = jnp.einsum("goi,ngip->ngop", wmat, cols).reshape(n, oc, oh, ow)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


# ------------------------------------------------------------- misc vision
def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    r = downscale_factor
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // r, r, w // r, r)
    x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
    return x.reshape(n, c * r * r, h // r, w // r)


def channel_shuffle(x, groups, data_format="NCHW"):
    n, c, h, w = x.shape
    x = x.reshape(n, groups, c // groups, h, w)
    return jnp.swapaxes(x, 1, 2).reshape(n, c, h, w)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    """col2im — inverse of unfold (phi fold_kernel). x: [N, C*kh*kw, L]."""
    oh, ow = _ntuple(output_sizes, 2)
    k = _ntuple(kernel_sizes, 2)
    s = _ntuple(strides, 2)
    p = _ntuple(paddings, 2)
    d = _ntuple(dilations, 2)
    n, ckk, l = x.shape
    c = ckk // (k[0] * k[1])
    nh = (oh + 2 * p[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
    nw = (ow + 2 * p[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
    cols = x.reshape(n, c, k[0], k[1], nh, nw)
    padded = jnp.zeros((n, c, oh + 2 * p[0], ow + 2 * p[1]), x.dtype)

    def add_tap(acc, tap):
        i, j = tap
        patch = cols[:, :, i, j]  # [n, c, nh, nw]
        ys = i * d[0] + jnp.arange(nh) * s[0]
        xs = j * d[1] + jnp.arange(nw) * s[1]
        return acc.at[:, :, ys[:, None], xs[None, :]].add(patch)

    for i in range(k[0]):
        for j in range(k[1]):
            padded = add_tap(padded, (i, j))
    return padded[:, :, p[0]:p[0] + oh, p[1]:p[1] + ow]


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW"):
    sq = jnp.square(x)
    half = size // 2
    pads = [(0, 0), (half, size - 1 - half), (0, 0), (0, 0)]
    div = lax.reduce_window(sq, _np.zeros((), x.dtype), lax.add,
                            (1, size, 1, 1), (1, 1, 1, 1), pads)
    return x / (k + alpha * div) ** beta


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS over [R, 4] boxes; returns kept indices sorted by score.
    O(R^2) IoU matrix + sequential suppression via fori_loop (static shape;
    the reference's phi nms_kernel is the same greedy algorithm)."""
    r = boxes.shape[0]
    if scores is None:
        order = jnp.arange(r)
    else:
        order = jnp.argsort(-scores)
    b = boxes[order]
    x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    xx1 = jnp.maximum(x1[:, None], x1[None, :])
    yy1 = jnp.maximum(y1[:, None], y1[None, :])
    xx2 = jnp.minimum(x2[:, None], x2[None, :])
    yy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(xx2 - xx1, 0) * jnp.maximum(yy2 - yy1, 0)
    iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter, 1e-10)
    if category_idxs is not None:
        cat = category_idxs[order]
        iou = jnp.where(cat[:, None] == cat[None, :], iou, 0.0)

    def body(i, keep):
        # suppress j > i overlapping a kept i
        sup = keep[i] & (iou[i] > iou_threshold)
        sup = sup & (jnp.arange(r) > i)
        return keep & ~sup

    keep = lax.fori_loop(0, r, body, jnp.ones((r,), bool))
    # variable-length result: eager-only, like the reference op
    kept = order[jnp.nonzero(keep, size=r, fill_value=-1)[0]]
    kept = kept[: int(jnp.sum(keep))]
    if top_k is not None:
        kept = kept[:top_k]
    return kept


# ------------------------------------------------------------- detection ops
def depthwise_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                     data_format="NCHW"):
    """phi depthwise_conv2d_kernel: conv2d with groups == in_channels.
    XLA fuses the grouped conv onto the MXU; no separate kernel needed."""
    from .nn_ops import conv2d

    channels = x.shape[3] if data_format == "NHWC" else x.shape[1]
    return conv2d(x, weight, bias=bias, stride=stride, padding=padding,
                  dilation=dilation, groups=channels,
                  data_format=data_format)


def depthwise_conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                               output_padding=0, dilation=1,
                               data_format="NCHW"):
    from .nn_ops import conv2d_transpose

    channels = x.shape[3] if data_format == "NHWC" else x.shape[1]
    return conv2d_transpose(x, weight, bias=bias, stride=stride,
                            padding=padding, output_padding=output_padding,
                            dilation=dilation, groups=channels,
                            data_format=data_format)


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, variance=None):
    """phi box_coder_kernel: encode/decode boxes against priors
    (center-size parameterization, SSD/Faster-RCNN)."""
    pb = prior_box
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + ph * 0.5
    if prior_box_var is not None:
        var = prior_box_var
    elif variance:
        var = jnp.asarray(variance, pb.dtype)[None, :]
    else:
        var = jnp.ones((1, 4), pb.dtype)
    if code_type == "encode_center_size":
        tb = target_box
        tw = tb[:, None, 2] - tb[:, None, 0] + norm
        th = tb[:, None, 3] - tb[:, None, 1] + norm
        tcx = tb[:, None, 0] + tw * 0.5
        tcy = tb[:, None, 1] + th * 0.5
        dx = (tcx - pcx[None, :]) / pw[None, :]
        dy = (tcy - pcy[None, :]) / ph[None, :]
        dw = jnp.log(jnp.abs(tw / pw[None, :]))
        dh = jnp.log(jnp.abs(th / ph[None, :]))
        out = jnp.stack([dx, dy, dw, dh], axis=-1)
        return out / var[None] if var.ndim == 2 else out / var
    # decode_center_size: target_box [N, M, 4] deltas
    tb = target_box
    if axis == 0:
        pw_, ph_, pcx_, pcy_ = (v[None, :] for v in (pw, ph, pcx, pcy))
        v4 = var[None] if var.shape[0] != 1 else var[None]
    else:
        pw_, ph_, pcx_, pcy_ = (v[:, None] for v in (pw, ph, pcx, pcy))
        v4 = var[:, None] if var.shape[0] != 1 else var[None]
    d = tb * v4
    ocx = d[..., 0] * pw_ + pcx_
    ocy = d[..., 1] * ph_ + pcy_
    ow = jnp.exp(d[..., 2]) * pw_
    oh = jnp.exp(d[..., 3]) * ph_
    return jnp.stack([ocx - ow * 0.5, ocy - oh * 0.5,
                      ocx + ow * 0.5 - norm, ocy + oh * 0.5 - norm], axis=-1)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variances=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False):
    """phi prior_box_kernel (SSD): anchor boxes per feature-map cell."""
    import numpy as np

    fh, fw = input.shape[2], input.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    boxes = []
    for k, ms in enumerate(min_sizes):
        ms = float(ms)
        if min_max_aspect_ratios_order:
            boxes.append((ms, ms))
            if max_sizes:
                mx = float(max_sizes[k])
                boxes.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                boxes.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                boxes.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if max_sizes:
                mx = float(max_sizes[k])
                boxes.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    wh = jnp.asarray(boxes, jnp.float32)  # [P, 2]
    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)  # [fh, fw]
    cxy = jnp.stack([cxg, cyg], -1)[:, :, None, :]      # [fh,fw,1,2]
    half = wh[None, None, :, :] / 2.0
    mins = (cxy - half) / jnp.asarray([iw, ih], jnp.float32)
    maxs = (cxy + half) / jnp.asarray([iw, ih], jnp.float32)
    out = jnp.concatenate([mins, maxs], axis=-1)  # [fh, fw, P, 4]
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), out.shape)
    return out, var


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """phi yolo_box_kernel: decode YOLOv3 head to boxes+scores."""
    n, c, h, w = x.shape
    an = len(anchors) // 2
    anc = jnp.asarray(anchors, jnp.float32).reshape(an, 2)
    if iou_aware:
        # phi layout: leading an channels are the iou block, then boxes block
        iou_pred = jax.nn.sigmoid(x[:, :an])            # [n, an, h, w]
        xr = x[:, an:].reshape(n, an, -1, h, w)
    else:
        iou_pred = None
        xr = x.reshape(n, an, -1, h, w)  # [n, an, 5+cls, h, w]
    gx = (jax.nn.sigmoid(xr[:, :, 0]) - 0.5) * scale_x_y + 0.5
    gy = (jax.nn.sigmoid(xr[:, :, 1]) - 0.5) * scale_x_y + 0.5
    cxg = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    cyg = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    bx = (gx + cxg) / w
    by = (gy + cyg) / h
    input_size = downsample_ratio * jnp.asarray([w, h], jnp.float32)
    bw = jnp.exp(xr[:, :, 2]) * anc[None, :, 0, None, None] / input_size[0]
    bh = jnp.exp(xr[:, :, 3]) * anc[None, :, 1, None, None] / input_size[1]
    conf = jax.nn.sigmoid(xr[:, :, 4])
    if iou_aware:
        conf = conf ** (1.0 - iou_aware_factor) * iou_pred ** iou_aware_factor
    probs = jax.nn.sigmoid(xr[:, :, 5:5 + class_num]) * conf[:, :, None]
    imgh = img_size[:, 0].astype(jnp.float32)[:, None]
    imgw = img_size[:, 1].astype(jnp.float32)[:, None]
    flat = lambda t: t.reshape(n, -1)
    x0 = (flat(bx) - flat(bw) / 2) * imgw
    y0 = (flat(by) - flat(bh) / 2) * imgh
    x1 = (flat(bx) + flat(bw) / 2) * imgw
    y1 = (flat(by) + flat(bh) / 2) * imgh
    if clip_bbox:
        x0 = jnp.clip(x0, 0, imgw - 1)
        x1 = jnp.clip(x1, 0, imgw - 1)
        y0 = jnp.clip(y0, 0, imgh - 1)
        y1 = jnp.clip(y1, 0, imgh - 1)
    boxes = jnp.stack([x0, y0, x1, y1], axis=-1)
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
    keep = (flat(conf) > conf_thresh)[..., None]
    return boxes * keep, scores * keep


def psroi_pool(x, boxes, boxes_num, output_channels, spatial_scale=1.0,
               pooled_height=1, pooled_width=1):
    """phi psroi_pool_kernel: position-sensitive average ROI pooling (R-FCN).
    Channel c*ph*pw + i*pw + j pools bin (i, j) of output channel c."""
    n, c, h, w = x.shape
    ph, pw = pooled_height, pooled_width
    assert c == output_channels * ph * pw
    # boxes_num is static per trace (host ints) — same contract as
    # roi_align's boxes_num
    counts = _np.asarray(boxes_num)
    batch_idx = jnp.asarray(_np.repeat(_np.arange(len(counts)), counts), jnp.int32)

    def pool_one(b, box):
        x0, y0, x1, y1 = box * spatial_scale
        rh = jnp.maximum(y1 - y0, 0.1) / ph
        rw = jnp.maximum(x1 - x0, 0.1) / pw
        ys = jnp.arange(h, dtype=jnp.float32)
        xs = jnp.arange(w, dtype=jnp.float32)
        out = jnp.zeros((output_channels, ph, pw), x.dtype)
        feat = x[b]
        for i in range(ph):
            for j in range(pw):
                y_lo = jnp.floor(y0 + i * rh)
                y_hi = jnp.ceil(y0 + (i + 1) * rh)
                x_lo = jnp.floor(x0 + j * rw)
                x_hi = jnp.ceil(x0 + (j + 1) * rw)
                my = ((ys >= y_lo) & (ys < y_hi)).astype(x.dtype)
                mx = ((xs >= x_lo) & (xs < x_hi)).astype(x.dtype)
                mask = my[:, None] * mx[None, :]
                area = jnp.maximum(jnp.sum(mask), 1.0)
                chans = feat[(jnp.arange(output_channels) * ph + i) * pw + j]
                out = out.at[:, i, j].set(jnp.sum(chans * mask[None], (1, 2)) / area)
        return out

    return jax.vmap(pool_one)(batch_idx, boxes)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None):
    """phi distribute_fpn_proposals: assign each ROI to an FPN level by its
    scale. Returns (per-level rois list, restore index) with STATIC shapes:
    each level gets the full roi tensor with non-member rows zeroed (the
    TPU-friendly masked formulation)."""
    off = 1.0 if pixel_offset else 0.0
    ws = fpn_rois[:, 2] - fpn_rois[:, 0] + off
    hs = fpn_rois[:, 3] - fpn_rois[:, 1] + off
    scale = jnp.sqrt(jnp.maximum(ws * hs, 1e-6))
    lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    outs = []
    for l in range(min_level, max_level + 1):
        m = (lvl == l).astype(fpn_rois.dtype)[:, None]
        outs.append(fpn_rois * m)
    order = jnp.argsort(lvl, stable=True)
    restore = jnp.argsort(order, stable=True)
    return (*outs, restore.astype(jnp.int32))


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=100, keep_top_k=100, use_gaussian=False,
               gauss_sigma=2.0, background_label=0, normalized=True):
    """phi matrix_nms_kernel (SOLOv2): soft decay of scores by pairwise IoU —
    fully parallel, no sequential suppression loop; TPU-native NMS."""
    c, m = scores.shape[0], scores.shape[1]
    norm = 0.0 if normalized else 1.0
    if 0 <= background_label < c:
        scores = scores.at[background_label].set(0.0)

    def area(b):
        return jnp.maximum(b[:, 2] - b[:, 0] + norm, 0) * jnp.maximum(
            b[:, 3] - b[:, 1] + norm, 0)

    def iou(b):
        lt = jnp.maximum(b[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(b[:, None, 2:], b[None, :, 2:])
        wh = jnp.maximum(rb - lt + norm, 0)
        inter = wh[..., 0] * wh[..., 1]
        a = area(b)
        return inter / jnp.maximum(a[:, None] + a[None, :] - inter, 1e-10)

    k = min(nms_top_k, m)

    def per_class(cls_scores):
        s, idx = jax.lax.top_k(cls_scores, k)
        b = bboxes[idx]
        m_iou = iou(b)
        upper = jnp.triu(m_iou, k=1)        # iou[i, j] for i higher-scored
        comp = jnp.max(upper, axis=0)       # compensate: max iou of i itself
        if use_gaussian:
            decay = jnp.exp(-(upper ** 2 - comp[:, None] ** 2) / gauss_sigma)
        else:
            decay = (1.0 - upper) / jnp.maximum(1.0 - comp[:, None], 1e-10)
        # only rows i < j participate; pad the rest with 1 (no decay)
        tri = jnp.triu(jnp.ones_like(upper), k=1) > 0
        dec = jnp.min(jnp.where(tri, decay, 1.0), axis=0)
        s2 = s * dec * (s > score_threshold)
        s2 = s2 * (s2 > post_threshold)
        return s2, idx, b

    all_s, all_i, all_b = jax.vmap(per_class)(scores)
    flat_s = all_s.reshape(-1)
    cls_id = jnp.repeat(jnp.arange(c), k)
    kk = min(keep_top_k, flat_s.shape[0])
    top_s, top_pos = jax.lax.top_k(flat_s, kk)
    out_boxes = all_b.reshape(-1, 4)[top_pos]
    out = jnp.concatenate([cls_id[top_pos][:, None].astype(bboxes.dtype),
                           top_s[:, None], out_boxes], axis=1)
    valid = (top_s > 0).astype(bboxes.dtype)[:, None]
    return out * valid, jnp.sum(top_s > 0).astype(jnp.int32)


def multiclass_nms3(bboxes, scores, score_threshold=0.05, nms_top_k=400,
                    keep_top_k=100, nms_threshold=0.45, normalized=True,
                    nms_eta=1.0, background_label=-1, rois_num=None):
    """phi multiclass_nms3: per-class hard NMS then global top-k. Static
    shapes: returns [keep_top_k, 6] with zero rows past the valid count."""
    c, m = scores.shape
    k = min(nms_top_k, m)
    norm = 0.0 if normalized else 1.0
    if 0 <= background_label < c:
        scores = scores.at[background_label].set(0.0)

    def keep_mask(b):
        """Greedy suppression keep-mask over score-sorted boxes."""
        a = jnp.maximum(b[:, 2] - b[:, 0] + norm, 0) * jnp.maximum(
            b[:, 3] - b[:, 1] + norm, 0)
        lt = jnp.maximum(b[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(b[:, None, 2:], b[None, :, 2:])
        wh = jnp.maximum(rb - lt + norm, 0)
        inter = wh[..., 0] * wh[..., 1]
        iou = inter / jnp.maximum(a[:, None] + a[None, :] - inter, 1e-10)

        def body(i, keep):
            sup = keep[i] & (iou[i] > nms_threshold) & (jnp.arange(k) > i)
            return keep & ~sup

        return lax.fori_loop(0, k, body, jnp.ones((k,), bool))

    def per_class(cls_scores):
        s, idx = jax.lax.top_k(cls_scores, k)
        b = bboxes[idx]
        keep = keep_mask(b)
        s2 = s * keep * (s > score_threshold)
        return s2, b

    all_s, all_b = jax.vmap(per_class)(scores)
    flat_s = all_s.reshape(-1)
    cls_id = jnp.repeat(jnp.arange(c), k)
    kk = min(keep_top_k, flat_s.shape[0])
    top_s, top_pos = jax.lax.top_k(flat_s, kk)
    out = jnp.concatenate([
        cls_id[top_pos][:, None].astype(bboxes.dtype),
        top_s[:, None], all_b.reshape(-1, 4)[top_pos]], axis=1)
    valid = (top_s > 0)
    return out * valid[:, None].astype(bboxes.dtype), jnp.sum(valid).astype(jnp.int32)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCDHW"):
    """Scatter pooled values back to argmax positions (phi unpool3d)."""
    k = _ntuple(kernel_size, 3)
    s = _ntuple(stride, 3) if stride is not None else k
    p = _ntuple(padding, 3)
    n, c, od, oh, ow = x.shape
    if output_size is None:
        d = (od - 1) * s[0] - 2 * p[0] + k[0]
        h = (oh - 1) * s[1] - 2 * p[1] + k[1]
        w = (ow - 1) * s[2] - 2 * p[2] + k[2]
    else:
        d, h, w = output_size[-3:]
    flat = jnp.zeros((n, c, d * h * w), x.dtype)
    idx = indices.reshape(n, c, -1).astype(jnp.int32)
    vals = x.reshape(n, c, -1)
    out = jax.vmap(jax.vmap(lambda f, i, v: f.at[i].set(v)))(flat, idx, vals)
    return out.reshape(n, c, d, h, w)


# reference-name aliases (phi yaml names)
unpool = max_unpool2d
unpool3d = max_unpool3d
max_pool2d_with_index = max_pool2d_with_mask


def pool3d(x, kernel_size, stride=None, padding=0, pooling_type="max",
           ceil_mode=False, exclusive=True, adaptive=False,
           data_format="NCDHW", global_pooling=False):
    """legacy pool3d op: one entry dispatching on pooling_type."""
    if global_pooling:
        kernel_size = x.shape[1:4] if data_format == "NDHWC" else x.shape[2:5]
        stride, padding = kernel_size, 0
    if adaptive:
        if pooling_type == "max":
            return adaptive_max_pool3d(x, kernel_size)
        return adaptive_avg_pool3d(x, kernel_size)
    if pooling_type == "max":
        return max_pool3d(x, kernel_size, stride, padding,
                          ceil_mode=ceil_mode, data_format=data_format)
    return avg_pool3d(x, kernel_size, stride, padding, exclusive=exclusive,
                      ceil_mode=ceil_mode, data_format=data_format)


def max_pool3d_with_index(x, kernel_size, stride=None, padding=0,
                          ceil_mode=False):
    return max_pool3d(x, kernel_size, stride, padding, return_mask=True,
                      ceil_mode=ceil_mode)


deformable_conv = deform_conv2d


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances=None,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False):
    """phi generate_proposals (RPN): decode anchor deltas, clip to the image,
    filter tiny boxes, NMS, keep post_nms_top_n. Static shapes: returns
    [post_nms_top_n, 4] boxes + scores with zero rows past the valid count.
    Single image (N=1 slice), like the phi kernel's per-image loop body."""
    off = 1.0 if pixel_offset else 0.0
    s = scores.reshape(-1)                       # [A*H*W]
    d = bbox_deltas.reshape(-1, 4)
    a = anchors.reshape(-1, 4)
    if variances is not None:
        d = d * variances.reshape(-1, 4)
    aw = a[:, 2] - a[:, 0] + off
    ah = a[:, 3] - a[:, 1] + off
    acx = a[:, 0] + aw * 0.5
    acy = a[:, 1] + ah * 0.5
    cx = d[:, 0] * aw + acx
    cy = d[:, 1] * ah + acy
    w = jnp.exp(jnp.minimum(d[:, 2], 10.0)) * aw
    h = jnp.exp(jnp.minimum(d[:, 3], 10.0)) * ah
    imh, imw = img_size[0], img_size[1]
    x0 = jnp.clip(cx - w * 0.5, 0, imw - off)
    y0 = jnp.clip(cy - h * 0.5, 0, imh - off)
    x1 = jnp.clip(cx + w * 0.5 - off, 0, imw - off)
    y1 = jnp.clip(cy + h * 0.5 - off, 0, imh - off)
    boxes = jnp.stack([x0, y0, x1, y1], axis=1)
    valid = ((x1 - x0 + off) >= min_size) & ((y1 - y0 + off) >= min_size)
    s = jnp.where(valid, s, -jnp.inf)

    k = min(int(pre_nms_top_n), s.shape[0])
    top_s, idx = jax.lax.top_k(s, k)
    b = boxes[idx]

    # greedy NMS keep-mask over score-sorted boxes
    area = jnp.maximum(b[:, 2] - b[:, 0] + off, 0) * jnp.maximum(
        b[:, 3] - b[:, 1] + off, 0)
    lt = jnp.maximum(b[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(b[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt + off, 0)
    inter = wh[..., 0] * wh[..., 1]
    iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter, 1e-10)

    def body(i, carry):
        keep, th = carry
        sup = keep[i] & (iou[i] > th) & (jnp.arange(k) > i)
        # adaptive NMS (eta < 1): decay the threshold while it stays > 0.5
        th = jnp.where((eta < 1.0) & (th > 0.5), th * eta, th)
        return keep & ~sup, th

    keep, _ = lax.fori_loop(0, k, body,
                            (jnp.ones((k,), bool),
                             jnp.asarray(nms_thresh, jnp.float32)))
    keep = keep & jnp.isfinite(top_s)
    final_s = jnp.where(keep, top_s, -jnp.inf)
    kk = min(int(post_nms_top_n), k)
    out_s, pos = jax.lax.top_k(final_s, kk)
    out_b = b[pos]
    ok = jnp.isfinite(out_s)
    return (out_b * ok[:, None], jnp.where(ok, out_s, 0.0),
            jnp.sum(ok).astype(jnp.int32))


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh=0.7, downsample_ratio=32, gt_score=None,
              use_label_smooth=False, scale_x_y=1.0):
    """phi yolov3_loss: coordinate + objectness + class loss for one YOLOv3
    head. x: [N, mask*(5+C), H, W]; gt_box: [N, B, 4] (xywh, image-relative
    0..1); gt_label: [N, B] int. Returns per-image loss [N]."""
    n, _, h, w = x.shape
    mask = list(anchor_mask)
    an = len(mask)
    anc_all = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    anc = anc_all[jnp.asarray(mask)]
    xr = x.reshape(n, an, 5 + class_num, h, w)
    input_size = downsample_ratio * jnp.asarray([w, h], jnp.float32)

    px = (jax.nn.sigmoid(xr[:, :, 0]) - 0.5) * scale_x_y + 0.5
    py = (jax.nn.sigmoid(xr[:, :, 1]) - 0.5) * scale_x_y + 0.5
    pw = xr[:, :, 2]
    ph = xr[:, :, 3]
    pobj = xr[:, :, 4]
    pcls = xr[:, :, 5:]

    gx = gt_box[..., 0] * w                        # [N, B] in grid units
    gy = gt_box[..., 1] * h
    gw = gt_box[..., 2]                            # image-relative
    gh = gt_box[..., 3]
    gi = jnp.clip(gx.astype(jnp.int32), 0, w - 1)
    gj = jnp.clip(gy.astype(jnp.int32), 0, h - 1)
    valid = (gw > 0) & (gh > 0)

    # responsible anchor: best iou of gt wh vs all anchors (shape-only iou)
    gw_pix = gw * input_size[0]
    gh_pix = gh * input_size[1]
    inter = (jnp.minimum(gw_pix[..., None], anc_all[None, None, :, 0])
             * jnp.minimum(gh_pix[..., None], anc_all[None, None, :, 1]))
    union = (gw_pix * gh_pix)[..., None] + (anc_all[:, 0] * anc_all[:, 1])[None, None] - inter
    best = jnp.argmax(inter / jnp.maximum(union, 1e-10), axis=-1)  # [N, B]
    # local anchor slot in this head (-1 when the best anchor isn't masked here)
    local = jnp.full(best.shape, -1, jnp.int32)
    for li, m in enumerate(mask):
        local = jnp.where(best == m, li, local)
    resp = valid & (local >= 0)

    tx = gx - jnp.floor(gx)
    ty = gy - jnp.floor(gy)
    tw = jnp.log(jnp.maximum(gw_pix / jnp.maximum(anc[jnp.clip(local, 0, an - 1), 0], 1e-10), 1e-10))
    th = jnp.log(jnp.maximum(gh_pix / jnp.maximum(anc[jnp.clip(local, 0, an - 1), 1], 1e-10), 1e-10))
    box_scale = 2.0 - gw * gh                      # small-box upweighting

    bidx = jnp.arange(n)[:, None]
    lidx = jnp.clip(local, 0, an - 1)

    def at(pred):
        return pred[bidx, lidx, gj, gi]            # [N, B]

    score_w = (jnp.ones_like(gx) if gt_score is None
               else gt_score.astype(jnp.float32))
    rw = resp.astype(jnp.float32) * box_scale * score_w
    delta = jnp.sum(rw * (jnp.abs(at(px) - tx) ** 2 + jnp.abs(at(py) - ty) ** 2
                          + jnp.abs(at(pw) - tw) ** 2 + jnp.abs(at(ph) - th) ** 2),
                    axis=1)

    # objectness: positives at responsible cells; negatives elsewhere unless
    # the cell's best iou with any gt exceeds ignore_thresh (decoded boxes)
    obj_t = jnp.zeros((n, an, h, w))
    obj_t = obj_t.at[bidx, lidx, gj, gi].add(
        resp.astype(jnp.float32) * score_w)
    obj_t = jnp.clip(obj_t, 0.0, 1.0)

    cxg = (jnp.arange(w, dtype=jnp.float32) + 0.0)[None, None, None, :]
    cyg = (jnp.arange(h, dtype=jnp.float32) + 0.0)[None, None, :, None]
    bx = (px + cxg) / w
    by = (py + cyg) / h
    bw = jnp.exp(jnp.clip(pw, -10, 10)) * anc[None, :, 0, None, None] / input_size[0]
    bh = jnp.exp(jnp.clip(ph, -10, 10)) * anc[None, :, 1, None, None] / input_size[1]
    px0, py0 = bx - bw / 2, by - bh / 2
    px1, py1 = bx + bw / 2, by + bh / 2
    gx0 = (gt_box[..., 0] - gt_box[..., 2] / 2)
    gy0 = (gt_box[..., 1] - gt_box[..., 3] / 2)
    gx1 = (gt_box[..., 0] + gt_box[..., 2] / 2)
    gy1 = (gt_box[..., 1] + gt_box[..., 3] / 2)
    ix0 = jnp.maximum(px0[..., None], gx0[:, None, None, None, :])
    iy0 = jnp.maximum(py0[..., None], gy0[:, None, None, None, :])
    ix1 = jnp.minimum(px1[..., None], gx1[:, None, None, None, :])
    iy1 = jnp.minimum(py1[..., None], gy1[:, None, None, None, :])
    iw = jnp.maximum(ix1 - ix0, 0)
    ih = jnp.maximum(iy1 - iy0, 0)
    inter2 = iw * ih
    area_p = bw * bh
    area_g = (gt_box[..., 2] * gt_box[..., 3])[:, None, None, None, :]
    iou2 = inter2 / jnp.maximum(area_p[..., None] + area_g - inter2, 1e-10)
    iou2 = jnp.where(valid[:, None, None, None, :], iou2, 0.0)
    best_iou = jnp.max(iou2, axis=-1)
    noobj_mask = (best_iou < ignore_thresh) & (obj_t < 0.5)

    def bce(logit, target):
        return jnp.maximum(logit, 0) - logit * target + jnp.log1p(jnp.exp(-jnp.abs(logit)))

    obj_loss = jnp.sum(bce(pobj, obj_t) * (obj_t + noobj_mask), axis=(1, 2, 3))

    # classification at responsible cells (label smoothing: phi uses
    # target = onehot*(1-eps) + eps/C with eps = 1/C)
    eps = (1.0 / max(class_num, 1)) if use_label_smooth else 0.0
    lab = jnp.clip(gt_label.astype(jnp.int32), 0, class_num - 1)
    cls_t = (jax.nn.one_hot(lab, class_num) * (1.0 - eps)
             + eps / max(class_num, 1))
    pcls_at = pcls[bidx, lidx, :, gj, gi]          # [N, B, C]
    cls_loss = jnp.sum(
        (resp.astype(jnp.float32) * score_w)[..., None] * bce(pcls_at, cls_t),
        axis=(1, 2))

    return delta + obj_loss + cls_loss


def read_file(filename):
    """paddle.vision.ops.read_file: file bytes as a uint8 tensor."""
    with open(filename, "rb") as f:
        data = f.read()
    return jnp.frombuffer(data, jnp.uint8)


def decode_jpeg(x, mode="unchanged"):
    """phi decode_jpeg (host decode, like the reference's CPU libjpeg path;
    the GPU nvjpeg variant has no TPU analog). x: uint8 byte tensor.
    Returns [C, H, W] uint8. Eager-only (data-dependent output shape)."""
    import io as _io

    import numpy as np_
    from PIL import Image

    raw = bytes(np_.asarray(x).astype(np_.uint8).tobytes())
    img = Image.open(_io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np_.asarray(img)
    if arr.ndim == 2:
        arr = arr[None, :, :]
    else:
        arr = arr.transpose(2, 0, 1)
    return jnp.asarray(arr)
