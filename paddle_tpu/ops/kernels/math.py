"""Elementwise & scalar math kernels.

Reference surface: paddle/phi/kernels/cpu|gpu/{elementwise_*,activation_*,...}
declared in paddle/phi/api/yaml/ops.yaml. Here each op is ONE pure jax function
(XLA emits the fused HLO); backward comes from jax.vjp at dispatch time, so the
reference's ~2x backward-kernel corpus is not needed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ...core.dtype import convert_dtype


def _promote_scalar(x, y):
    # paddle allows python scalars on either side
    return x, y


def add(x, y):
    return jnp.add(x, y)


def subtract(x, y):
    return jnp.subtract(x, y)


def multiply(x, y):
    return jnp.multiply(x, y)


def divide(x, y):
    return jnp.divide(x, y)


def floor_divide(x, y):
    return jnp.floor_divide(x, y)


def remainder(x, y):
    return jnp.remainder(x, y)


mod = remainder


def pow(x, y):
    return jnp.power(x, y)


def maximum(x, y):
    return jnp.maximum(x, y)


def minimum(x, y):
    return jnp.minimum(x, y)


def fmax(x, y):
    return jnp.fmax(x, y)


def fmin(x, y):
    return jnp.fmin(x, y)


def atan2(x, y):
    return jnp.arctan2(x, y)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


def exp(x):
    return jnp.exp(x)


def expm1(x):
    return jnp.expm1(x)


def log(x):
    return jnp.log(x)


def log2(x):
    return jnp.log2(x)


def log10(x):
    return jnp.log10(x)


def log1p(x):
    return jnp.log1p(x)


def sqrt(x):
    return jnp.sqrt(x)


def rsqrt(x):
    return lax.rsqrt(x)


def square(x):
    return jnp.square(x)


def abs(x):
    return jnp.abs(x)


def sign(x):
    return jnp.sign(x)


def neg(x):
    return jnp.negative(x)


def reciprocal(x):
    return jnp.reciprocal(x)


def floor(x):
    return jnp.floor(x)


def ceil(x):
    return jnp.ceil(x)


def round(x):
    # paddle rounds half AWAY FROM ZERO (std::round); jnp.round is
    # half-to-even
    if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
        return jnp.sign(x) * jnp.floor(jnp.abs(x) + 0.5)
    return jnp.asarray(x)


def trunc(x):
    return jnp.trunc(x)


def frac(x):
    return x - jnp.trunc(x)


def sin(x):
    return jnp.sin(x)


def cos(x):
    return jnp.cos(x)


def tan(x):
    return jnp.tan(x)


def asin(x):
    return jnp.arcsin(x)


def acos(x):
    return jnp.arccos(x)


def atan(x):
    return jnp.arctan(x)


def sinh(x):
    return jnp.sinh(x)


def cosh(x):
    return jnp.cosh(x)


def tanh(x):
    return jnp.tanh(x)


def asinh(x):
    return jnp.arcsinh(x)


def acosh(x):
    return jnp.arccosh(x)


def atanh(x):
    return jnp.arctanh(x)


def erf(x):
    return jax.scipy.special.erf(x)


def erfinv(x):
    return jax.scipy.special.erfinv(x)


def digamma(x):
    return jax.scipy.special.digamma(x)


def lgamma(x):
    return jax.scipy.special.gammaln(x)


def clip(x, min=None, max=None):
    return jnp.clip(x, min, max)


def lerp(x, y, weight):
    return x + weight * (y - x)


def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


def logit(x, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


def isnan(x):
    return jnp.isnan(x)


def isinf(x):
    return jnp.isinf(x)


def isfinite(x):
    return jnp.isfinite(x)


def cumsum(x, axis=None, dtype=None):
    if dtype is not None:
        dtype = convert_dtype(dtype)
    if axis is None:
        return jnp.cumsum(x.reshape(-1), dtype=dtype)
    return jnp.cumsum(x, axis=axis, dtype=dtype)


def cumprod(x, dim=None, dtype=None):
    if dtype is not None:
        dtype = convert_dtype(dtype)
    if dim is None:
        return jnp.cumprod(x.reshape(-1), dtype=dtype)
    return jnp.cumprod(x, axis=dim, dtype=dtype)


def _cum_argext(x, axis, op):
    """Running (values, indices) for cummax/cummin: scan over (value, idx)
    pairs keeping the FIRST extreme on ties, like the reference kernel."""
    idx0 = jnp.broadcast_to(
        jnp.expand_dims(jnp.arange(x.shape[axis]),
                        tuple(d for d in range(x.ndim) if d != axis)),
        x.shape)

    def comb(a, b):
        av, ai = a
        bv, bi = b
        take_b = op(bv, av) & (bv != av)
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

    vals, idxs = lax.associative_scan(comb, (x, idx0), axis=axis)
    return vals, idxs.astype(jnp.int64)


def cummax(x, axis=None):
    """Returns (values, indices), matching paddle.cummax."""
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return _cum_argext(x, axis, jnp.greater)


def cummin(x, axis=None):
    """Returns (values, indices), matching paddle.cummin."""
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return _cum_argext(x, axis, jnp.less)


def logaddexp(x, y):
    return jnp.logaddexp(x, y)


def logcumsumexp(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return lax.cumlogsumexp(x, axis=axis)


def heaviside(x, y):
    return jnp.heaviside(x, y)


def gcd(x, y):
    return jnp.gcd(x, y)


def lcm(x, y):
    return jnp.lcm(x, y)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


def angle(x):
    return jnp.angle(x)


def conj(x):
    return jnp.conj(x)


def real(x):
    return jnp.real(x)


def imag(x):
    return jnp.imag(x)


def rad2deg(x):
    return jnp.rad2deg(x)


def deg2rad(x):
    return jnp.deg2rad(x)


def diff(x, n=1, axis=-1, prepend=None, append=None):
    return jnp.diff(x, n=n, axis=axis, prepend=prepend, append=append)


def multiply_add(x, y, z):
    """fma: x * y + z (reference: fused elementwise)."""
    return x * y + z


def trapezoid(y, x=None, dx=None, axis=-1):
    return jnp.trapezoid(y, x=x, dx=1.0 if dx is None else dx, axis=axis)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1):
    y = jnp.asarray(y)
    nd = y.ndim
    axis = axis % nd
    sl1 = [slice(None)] * nd
    sl2 = [slice(None)] * nd
    sl1[axis] = slice(1, None)
    sl2[axis] = slice(None, -1)
    if x is not None:
        d = jnp.diff(jnp.asarray(x), axis=axis if jnp.asarray(x).ndim == nd else 0)
        if d.ndim != nd:
            shape = [1] * nd
            shape[axis] = d.shape[0]
            d = d.reshape(shape)
    else:
        d = 1.0 if dx is None else dx
    return jnp.cumsum(d * (y[tuple(sl1)] + y[tuple(sl2)]) / 2.0, axis=axis)


def copysign(x, y):
    return jnp.copysign(x, y)


def nextafter(x, y):
    return jnp.nextafter(x, y)


def hypot(x, y):
    return jnp.hypot(x, y)


def signbit(x):
    return jnp.signbit(x)


def ldexp(x, y):
    return jnp.ldexp(x, y.astype(jnp.int32))


def frexp(x):
    return jnp.frexp(x)


def i0(x):
    return jax.scipy.special.i0(x)


def i0e(x):
    return jax.scipy.special.i0e(x)


def i1(x):
    return jax.scipy.special.i1(x)


def i1e(x):
    return jax.scipy.special.i1e(x)


def polygamma(x, n):
    return jax.scipy.special.polygamma(n, x)


def igamma(x, a):
    """paddle.igamma(x, a) = regularized UPPER incomplete gamma with x as
    the shape parameter and a as the integral's lower limit (note the
    reference's unusual argument order): Q(x, a) = gammaincc(x, a)."""
    return jax.scipy.special.gammaincc(x, a)


def igammac(x, a):
    """Complement: the regularized LOWER incomplete gamma P(x, a)."""
    return jax.scipy.special.gammainc(x, a)


def sinc(x):
    return jnp.sinc(x)


def renorm(x, p, axis, max_norm):
    """Reference: phi renorm_kernel — scale each sub-tensor along `axis`
    whose p-norm exceeds max_norm down to exactly max_norm."""
    axes = tuple(i for i in range(x.ndim) if i != axis % x.ndim)
    norms = jnp.sum(jnp.abs(x) ** p, axis=axes, keepdims=True) ** (1.0 / p)
    scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return x * scale


def erfc(x):
    return jax.scipy.special.erfc(x)


def logaddexp2(x, y):
    return jnp.logaddexp2(x, y)


def sgn(x):
    if jnp.iscomplexobj(x):
        mag = jnp.abs(x)
        return jnp.where(mag == 0, 0, x / jnp.where(mag == 0, 1, mag))
    return jnp.sign(x)


def log_normalize(x, axis=-1):
    return x - jax.scipy.special.logsumexp(x, axis=axis, keepdims=True)


def elementwise_pow(x, y):
    """Reference name for tensor-tensor pow (legacy_ops.yaml elementwise_pow)."""
    return jnp.power(x, y)


def squared_l2_norm(x):
    """phi squared_l2_norm_kernel: sum of squares as a 0-d tensor."""
    return jnp.sum(jnp.square(x))


def frobenius_norm(x, axis=None, keepdim=False):
    if axis is None:
        axis = tuple(range(x.ndim))
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=tuple(jnp.atleast_1d(jnp.asarray(axis)).tolist()) if not isinstance(axis, (tuple, list)) else tuple(axis), keepdims=keepdim))


def clip_by_norm(x, max_norm):
    """phi clip_by_norm_kernel: scale x so ||x||_2 <= max_norm."""
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return jnp.where(norm > max_norm, x * (max_norm / jnp.maximum(norm, 1e-12)), x)


def increment(x, value=1.0):
    """legacy increment op: x + value (0-d/1-element tensors)."""
    return x + jnp.asarray(value, x.dtype)


def mean_all(x):
    """phi mean_all_kernel: mean over every element (0-d out)."""
    return jnp.mean(x)


def gammaln(x):
    return jax.scipy.special.gammaln(x)
