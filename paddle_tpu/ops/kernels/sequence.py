"""Sequence/decoding op kernels: beam-search backtrace, Viterbi, edit
distance, STFT framing.

Reference: phi gather_tree_kernel, viterbi_decode_kernel,
edit_distance_kernel (paddle/phi/kernels/cpu+gpu), and the paddle.signal
frame/overlap_add ops. TPU design: every recursion is a lax.scan (static
shapes); edit distance runs the Levenshtein DP as a scan over one sequence
with the row vectorized.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def gather_tree(ids, parents):
    """Beam-search backtrace. ids/parents: [T, B, W] (time-major, like the
    reference). Walks parent pointers from the last step back, emitting the
    full beam paths."""
    T = ids.shape[0]
    W = ids.shape[2]

    def step(beam_idx, t):
        # beam_idx: [B, W] — which beam each final slot follows at time t+1
        tok = jnp.take_along_axis(ids[t], beam_idx, axis=1)
        par = jnp.take_along_axis(parents[t], beam_idx, axis=1)
        return par.astype(jnp.int32), tok

    init = jnp.broadcast_to(jnp.arange(W, dtype=jnp.int32), ids.shape[1:])
    _, toks = lax.scan(step, init, jnp.arange(T - 1, -1, -1))
    return jnp.flip(toks, axis=0)


def viterbi_decode(potentials, transition, lengths, include_bos_eos_tag=True):
    """CRF Viterbi decoding (phi viterbi_decode_kernel).

    potentials: [B, T, N] emission scores; transition: [N, N] (with BOS=N-2,
    EOS=N-1 rows/cols when include_bos_eos_tag). Returns (scores [B],
    paths [B, T])."""
    B, T, N = potentials.shape
    trans = transition

    if include_bos_eos_tag:
        bos, eos = N - 2, N - 1
        alpha0 = potentials[:, 0, :] + trans[bos][None, :]
    else:
        alpha0 = potentials[:, 0, :]

    def step(carry, t):
        alpha = carry  # [B, N]
        scores = alpha[:, :, None] + trans[None, :, :] + potentials[:, t][:, None, :]
        best_prev = jnp.argmax(scores, axis=1).astype(jnp.int32)   # [B, N]
        new_alpha = jnp.max(scores, axis=1)
        # sequences already finished keep their alpha (masked by length)
        active = (t < lengths)[:, None]
        new_alpha = jnp.where(active, new_alpha, alpha)
        best_prev = jnp.where(active, best_prev,
                              jnp.broadcast_to(jnp.arange(N, dtype=jnp.int32),
                                               (B, N)))
        return new_alpha, best_prev

    alpha, history = lax.scan(step, alpha0, jnp.arange(1, T))
    if include_bos_eos_tag:
        alpha = alpha + trans[:, eos][None, :]
    last_tag = jnp.argmax(alpha, axis=-1).astype(jnp.int32)
    score = jnp.max(alpha, axis=-1)

    def back(tag, prev):
        new_tag = jnp.take_along_axis(prev, tag[:, None], axis=1)[:, 0]
        return new_tag, new_tag

    _, tags = lax.scan(back, last_tag, history, reverse=True)
    paths = jnp.concatenate([jnp.moveaxis(tags, 0, 1), last_tag[:, None]], 1)
    return score, paths


def edit_distance(hyps, refs, hyp_lengths, ref_lengths, normalized=False):
    """Levenshtein distance (phi edit_distance_kernel). hyps/refs: [B, Tmax]
    int token ids padded; lengths give the valid prefix. DP row recursion is
    a lax.scan over the hypothesis with the reference row vectorized via an
    associative min-plus prefix scan for the insertion chain."""
    B, Th = hyps.shape
    Tr = refs.shape[1]
    BIG = jnp.asarray(1e9, jnp.float32)

    def one(hyp, ref, hl, rl):
        row0 = jnp.arange(Tr + 1, dtype=jnp.float32)
        row0 = jnp.where(jnp.arange(Tr + 1) <= rl, row0, BIG)

        def step(row, i):
            valid_i = i < hl
            sub = row[:-1] + (ref != hyp[i]).astype(jnp.float32)
            dele = row[1:] + 1.0
            base = jnp.minimum(sub, dele)
            base = jnp.concatenate([jnp.array([i + 1.0]), base])
            # insertion chain: new[j] = min(base[j], new[j-1] + 1) — a
            # min-plus prefix scan: new[j] = min_k (base[k] + (j - k))
            shifted = base - jnp.arange(Tr + 1, dtype=jnp.float32)
            run_min = lax.associative_scan(jnp.minimum, shifted)
            new = run_min + jnp.arange(Tr + 1, dtype=jnp.float32)
            new = jnp.where(jnp.arange(Tr + 1) <= rl, new, BIG)
            return jnp.where(valid_i, new, row), None

        row, _ = lax.scan(step, row0, jnp.arange(Th))
        d = row[rl]
        if normalized:
            d = d / jnp.maximum(rl.astype(jnp.float32), 1.0)
        return d

    return jax.vmap(one)(hyps.astype(jnp.int32), refs.astype(jnp.int32),
                         hyp_lengths.astype(jnp.int32),
                         ref_lengths.astype(jnp.int32))


def frame(x, frame_length, hop_length, axis=-1):
    """paddle.signal.frame: sliding windows along `axis`.
    out last dims: [..., frame_length, num_frames] for axis=-1 (reference
    layout)."""
    if axis not in (-1, x.ndim - 1):
        x = jnp.moveaxis(x, axis, -1)
    n = x.shape[-1]
    num = 1 + (n - frame_length) // hop_length
    starts = jnp.arange(num) * hop_length
    idx = starts[None, :] + jnp.arange(frame_length)[:, None]  # [L, F]
    out = x[..., idx]  # [..., L, F]
    if axis not in (-1, x.ndim - 1):
        out = jnp.moveaxis(out, -1, axis)
    return out


def overlap_add(x, hop_length, axis=-1):
    """paddle.signal.overlap_add: inverse of frame (sum overlapping windows).
    x: [..., frame_length, num_frames] for axis=-1."""
    if axis not in (-1, x.ndim - 1):
        x = jnp.moveaxis(x, axis, -1)
    L, F = x.shape[-2], x.shape[-1]
    n = (F - 1) * hop_length + L
    starts = jnp.arange(F) * hop_length
    idx = (starts[None, :] + jnp.arange(L)[:, None]).reshape(-1)  # [L*F]
    flat = jnp.moveaxis(x, -1, -1).reshape(x.shape[:-2] + (L * F,))
    zeros = jnp.zeros(x.shape[:-2] + (n,), x.dtype)
    out = zeros.at[..., idx].add(flat)
    if axis not in (-1, x.ndim - 1):
        out = jnp.moveaxis(out, -1, axis)
    return out
