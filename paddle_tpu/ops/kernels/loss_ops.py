"""Loss op kernels beyond the core set in nn_ops.

Reference surface: python/paddle/nn/functional/loss.py (ctc_loss via warpctc,
margin_ranking_loss, triplet margin family, cosine_embedding_loss,
soft_margin family, poisson/gaussian NLL, square_error_cost, log_loss,
dice_loss, npair_loss). CTC here is a fresh log-domain alpha recursion staged
with lax.scan (static [T] loop, SPMD-friendly) rather than the reference's
dynloaded warpctc CUDA library.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC forward. log_probs: [T, N, C] (log-softmax applied here if the
    rows do not already sum to 1 in prob space is NOT checked — pass logits
    and we normalize, matching paddle which takes logits). labels: [N, L]
    padded; input_lengths/label_lengths: [N] int.
    """
    log_probs = jax.nn.log_softmax(log_probs, axis=-1)
    t_max, n, c = log_probs.shape
    l_max = labels.shape[1]
    labels = labels.astype(jnp.int32)
    input_lengths = jnp.asarray(input_lengths, jnp.int32)
    label_lengths = jnp.asarray(label_lengths, jnp.int32)

    s = 2 * l_max + 1
    ext = jnp.full((n, s), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    # alpha[s] may come from alpha[s-2] only if ext[s] != ext[s-2] and ext[s]
    # is not blank (the standard CTC skip rule)
    can_skip = jnp.concatenate(
        [jnp.zeros((n, 2), bool),
         (ext[:, 2:] != ext[:, :-2]) & (ext[:, 2:] != blank)], axis=1)
    # positions beyond 2*label_len are dead
    alive = jnp.arange(s)[None, :] < (2 * label_lengths + 1)[:, None]

    batch = jnp.arange(n)
    lp0 = log_probs[0]
    alpha0 = jnp.full((n, s), _NEG_INF)
    alpha0 = alpha0.at[:, 0].set(lp0[batch, ext[:, 0]])
    has_label = label_lengths > 0
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(has_label, lp0[batch, ext[:, 1]], _NEG_INF))

    def lse3(a, b, c3):
        m = jnp.maximum(jnp.maximum(a, b), c3)
        m_safe = jnp.maximum(m, _NEG_INF)
        out = m_safe + jnp.log(
            jnp.exp(a - m_safe) + jnp.exp(b - m_safe) + jnp.exp(c3 - m_safe))
        return jnp.where(m <= _NEG_INF, _NEG_INF, out)

    def step(alpha, tlp):
        t, lp = tlp
        a1 = jnp.concatenate([jnp.full((n, 1), _NEG_INF), alpha[:, :-1]], 1)
        a2 = jnp.concatenate([jnp.full((n, 2), _NEG_INF), alpha[:, :-2]], 1)
        a2 = jnp.where(can_skip, a2, _NEG_INF)
        emit = jnp.take_along_axis(lp, ext, axis=1)
        new = lse3(alpha, a1, a2) + emit
        new = jnp.where(alive, new, _NEG_INF)
        keep = (t < input_lengths)[:, None]
        return jnp.where(keep, new, alpha), None

    alpha_t, _ = lax.scan(step, alpha0,
                          (jnp.arange(1, t_max), log_probs[1:]))
    # total log-prob: lse of final blank (2L) and last label (2L-1)
    idx_label = jnp.maximum(2 * label_lengths - 1, 0)
    idx_blank = 2 * label_lengths
    a_label = jnp.where(has_label,
                        jnp.take_along_axis(alpha_t, idx_label[:, None], 1)[:, 0],
                        _NEG_INF)
    a_blank = jnp.take_along_axis(alpha_t, idx_blank[:, None], 1)[:, 0]
    m = jnp.maximum(a_label, a_blank)
    ll = m + jnp.log(jnp.exp(a_label - m) + jnp.exp(a_blank - m))
    nll = -ll
    if norm_by_times:
        nll = nll / input_lengths.astype(nll.dtype)
    if reduction == "mean":
        # paddle: per-sample loss divided by label length, then batch mean
        return jnp.mean(nll / jnp.maximum(label_lengths, 1).astype(nll.dtype))
    return _reduce(nll, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):
    loss = jnp.maximum(-label * (input - other) + margin, 0.0)
    return _reduce(loss, reduction)


def _p_norm(x, p, axis, eps=0.0):
    if p == 2.0:
        return jnp.sqrt(jnp.sum(x * x, axis=axis) + eps)
    return jnp.sum((jnp.abs(x) + eps) ** p, axis=axis) ** (1.0 / p)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False):
    d = x - y
    out = _p_norm(d, p, axis=-1, eps=epsilon if p == 2.0 else epsilon)
    return out[..., None] if keepdim else out


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean"):
    d_pos = pairwise_distance(input, positive, p, epsilon)
    d_neg = pairwise_distance(input, negative, p, epsilon)
    if swap:
        d_neg = jnp.minimum(d_neg, pairwise_distance(positive, negative, p, epsilon))
    loss = jnp.maximum(d_pos - d_neg + margin, 0.0)
    return _reduce(loss, reduction)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean"):
    dist = distance_function or (lambda a, b: pairwise_distance(a, b, 2.0))
    d_pos = dist(input, positive)
    d_neg = dist(input, negative)
    if swap:
        d_neg = jnp.minimum(d_neg, dist(positive, negative))
    loss = jnp.maximum(d_pos - d_neg + margin, 0.0)
    return _reduce(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean"):
    dot = jnp.sum(input1 * input2, axis=-1)
    n1 = jnp.sqrt(jnp.sum(input1 * input1, axis=-1))
    n2 = jnp.sqrt(jnp.sum(input2 * input2, axis=-1))
    cos = dot / jnp.maximum(n1 * n2, 1e-12)
    loss = jnp.where(label == 1, 1.0 - cos,
                     jnp.maximum(cos - margin, 0.0))
    return _reduce(loss, reduction)


def soft_margin_loss(input, label, reduction="mean"):
    loss = jnp.log1p(jnp.exp(-label * input))
    return _reduce(loss, reduction)


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean"):
    loss = -(label * jax.nn.log_sigmoid(input)
             + (1 - label) * jax.nn.log_sigmoid(-input))
    if weight is not None:
        loss = loss * weight
    loss = jnp.mean(loss, axis=-1)
    return _reduce(loss, reduction)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean"):
    n, c = input.shape
    label = label.astype(jnp.int32)
    x_y = jnp.take_along_axis(input, label[:, None], axis=1)  # [N, 1]
    diff = jnp.maximum(margin - x_y + input, 0.0) ** p
    if weight is not None:
        diff = diff * weight[label][:, None]
    mask = jax.nn.one_hot(label, c, dtype=input.dtype)
    loss = jnp.sum(diff * (1 - mask), axis=1) / c
    return _reduce(loss, reduction)


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean"):
    if log_input:
        loss = jnp.exp(input) - label * input
    else:
        loss = input - label * jnp.log(input + epsilon)
    if full:
        stirling = label * jnp.log(label) - label + 0.5 * jnp.log(
            2 * jnp.pi * label)
        loss = loss + jnp.where(label > 1, stirling, 0.0)
    return _reduce(loss, reduction)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean"):
    var = jnp.maximum(variance, epsilon)
    loss = 0.5 * (jnp.log(var) + (input - label) ** 2 / var)
    if full:
        loss = loss + 0.5 * jnp.log(2 * jnp.asarray(jnp.pi))
    return _reduce(loss, reduction)


def square_error_cost(input, label):
    return jnp.square(input - label)


def log_loss(input, label, epsilon=1e-4):
    return (-label * jnp.log(input + epsilon)
            - (1.0 - label) * jnp.log(1.0 - input + epsilon))


def dice_loss(input, label, epsilon=1e-5):
    # input: [N, ..., C] probabilities; label: [N, ..., 1] int
    label_one_hot = jax.nn.one_hot(label.squeeze(-1), input.shape[-1],
                                   dtype=input.dtype)
    reduce_axes = tuple(range(1, input.ndim))
    inter = 2.0 * jnp.sum(input * label_one_hot, axis=reduce_axes)
    union = jnp.sum(input, axis=reduce_axes) + jnp.sum(label_one_hot,
                                                       axis=reduce_axes)
    return jnp.mean(1.0 - (inter + epsilon) / (union + epsilon))


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """Reference: python/paddle/nn/functional/loss.py npair_loss — softmax CE
    over anchor·positiveᵀ similarity + L2 on the embeddings."""
    reg = l2_reg * (jnp.mean(jnp.sum(anchor * anchor, axis=1))
                    + jnp.mean(jnp.sum(positive * positive, axis=1))) * 0.25
    sim = anchor @ positive.T  # [N, N]
    labels = labels.reshape(-1)
    eq = (labels[:, None] == labels[None, :]).astype(sim.dtype)
    target = eq / jnp.sum(eq, axis=1, keepdims=True)
    logp = jax.nn.log_softmax(sim, axis=1)
    ce = -jnp.mean(jnp.sum(target * logp, axis=1))
    return ce + reg


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None):
    """Hierarchical sigmoid with the default complete binary tree
    (reference hsigmoid_loss_kernel). Only the default-tree path is
    implemented; custom path tables are rejected."""
    if path_table is not None or path_code is not None:
        raise NotImplementedError("custom hsigmoid trees not supported")
    # default tree: codes are the bits of (label + num_classes) walking down
    n = input.shape[0]
    depth = max(int(num_classes - 1).bit_length(), 1)
    code = label.astype(jnp.int32) + num_classes
    # walk up: parent chain node ids (root excluded), bit = left/right
    losses = jnp.zeros((n,), input.dtype)
    x_w = input @ weight.T  # [N, num_classes-1] pre-activations
    if bias is not None:
        x_w = x_w + bias.reshape(1, -1)
    for _ in range(depth):
        parent = code // 2
        bit = (code % 2).astype(input.dtype)  # 1 => right child
        valid = parent >= 1
        idx = jnp.clip(parent - 1, 0, num_classes - 2)
        logits = jnp.take_along_axis(x_w, idx[:, None], axis=1)[:, 0]
        # sigmoid CE with target = bit
        step_loss = jnp.maximum(logits, 0) - logits * bit + jnp.log1p(
            jnp.exp(-jnp.abs(logits)))
        losses = losses + jnp.where(valid, step_loss, 0.0)
        code = parent
    return losses[:, None]


def huber_loss(input, label, delta=1.0):
    """phi huber_loss_kernel (NOT smooth_l1: no /delta normalization)."""
    r = input - label
    a = jnp.abs(r)
    return jnp.where(a <= delta, 0.5 * r * r, delta * (a - 0.5 * delta))


def sigmoid_cross_entropy_with_logits(x, label, normalize=False,
                                      ignore_index=-100, pos_weight=None):
    """phi sigmoid_cross_entropy_with_logits_kernel."""
    valid = (label != ignore_index)
    lab = jnp.where(valid, label, 0).astype(x.dtype)
    # stable BCE-with-logits
    base = jnp.maximum(x, 0) - x * lab + jnp.log1p(jnp.exp(-jnp.abs(x)))
    if pos_weight is not None:
        w = 1.0 + (pos_weight - 1.0) * lab
        base = base * w
    out = jnp.where(valid, base, 0.0)
    if normalize:
        out = out / jnp.maximum(jnp.sum(valid.astype(x.dtype)), 1.0)
    return out


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, return_softmax=False):
    """phi margin_cross_entropy (ArcFace-family margin softmax):
    cos(m1*theta + m2) - m3 applied to the target logit, then scaled CE."""
    # clip strictly inside [-1, 1]: arccos' is infinite at the boundary and
    # a single cos==1.0 sample (embedding vs its own center) would NaN the
    # whole gradient
    t = jnp.clip(logits, -1.0 + 1e-7, 1.0 - 1e-7)
    theta = jnp.arccos(t)
    target_theta = jnp.take_along_axis(theta, label[:, None].astype(jnp.int32), 1)
    target = jnp.cos(margin1 * target_theta + margin2) - margin3
    oh = jax.nn.one_hot(label.astype(jnp.int32), logits.shape[-1], dtype=t.dtype)
    adj = t * (1.0 - oh) + target * oh
    z = adj * scale
    logp = jax.nn.log_softmax(z, axis=-1)
    loss = -jnp.take_along_axis(logp, label[:, None].astype(jnp.int32), 1)
    if return_softmax:
        return loss, jnp.exp(logp)
    return loss


def rnnt_loss(logits, labels, logit_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0):
    """RNN-Transducer loss (phi warprnnt analog), TPU-native formulation.

    logits: [B, T, U+1, V] joint-network log-probs (unnormalized ok);
    labels: [B, U] int32. The alpha recursion
        a[t,u] = logaddexp(a[t-1,u] + blank(t-1,u), a[t,u-1] + emit(t,u-1))
    runs as a lax.scan over T whose body solves the u-recursion with an
    associative scan in the (log,+) semiring — first-order linear recurrences
    compose associatively as affine maps (c2, b2)o(c1, b1) =
    (c1+c2, logaddexp(b2, c2+b1)) — so each step is O(log U) depth instead
    of a sequential U loop.
    """
    if fastemit_lambda:
        # FastEmit (Yu et al. 2021, as in warprnnt/torchaudio): the LOSS is
        # the standard transducer loss; the GRADIENT's emit component is
        # scaled by (1+lambda). Needs the analytic alpha-beta gradient, so
        # it routes through the custom-vjp path.
        return _rnnt_loss_fastemit(logits, labels, logit_lengths,
                                   label_lengths, blank,
                                   float(fastemit_lambda))
    lp = jax.nn.log_softmax(logits, axis=-1)
    B, T, U1, V = lp.shape
    U = U1 - 1
    blank_lp = lp[..., blank]                                  # [B, T, U+1]
    lab = labels.astype(jnp.int32)
    emit_lp = jnp.take_along_axis(
        lp[:, :, :U, :], lab[:, None, :, None], axis=-1)[..., 0]  # [B,T,U]
    NEG = -1e30

    def solve_row(base, c):
        """y[u] = logaddexp(base[u], y[u-1] + c[u-1]); y[-1] = -inf."""
        cs = jnp.concatenate([jnp.full(c.shape[:-1] + (1,), NEG), c[..., :-1]],
                             axis=-1)

        def comb(l, r):
            cl, bl = l
            cr, br = r
            return cl + cr, jnp.logaddexp(br, cr + bl)

        _, y = jax.lax.associative_scan(comb, (cs, base), axis=-1)
        return y

    def step(alpha_prev, t):
        # base: from the T-direction (blank transition t-1 -> t)
        init0 = jnp.concatenate(
            [jnp.zeros((B, 1)), jnp.full((B, U), NEG)], -1)
        base = jnp.where(t == 0, init0,
                         alpha_prev + blank_lp[:, jnp.maximum(t - 1, 0), :])
        # u-recursion: emit transition (t, u-1) -> (t, u); pad so
        # solve_row's right-shift yields cs[u] = emit[u-1]
        c_in = jnp.concatenate(
            [emit_lp[:, t, :], jnp.full((B, 1), NEG)], -1)
        alpha = solve_row(base, c_in)
        return alpha, alpha

    alpha0 = jnp.full((B, U1), NEG)
    _, alphas = jax.lax.scan(step, alpha0, jnp.arange(T))      # [T, B, U+1]
    alphas = jnp.moveaxis(alphas, 0, 1)                        # [B, T, U+1]
    tl = logit_lengths.astype(jnp.int32)
    ul = label_lengths.astype(jnp.int32)
    a_final = jnp.take_along_axis(
        jnp.take_along_axis(alphas, (tl - 1)[:, None, None], axis=1)[:, 0, :],
        ul[:, None], axis=1)[:, 0]
    final_blank = jnp.take_along_axis(
        jnp.take_along_axis(blank_lp, (tl - 1)[:, None, None], axis=1)[:, 0, :],
        ul[:, None], axis=1)[:, 0]
    return -(a_final + final_blank)


def _rnnt_alpha_beta(logits, labels, logit_lengths, label_lengths, blank):
    """Full lattice quantities for the analytic transducer gradient:
    returns (loss [B], alphas, betas, blank_lp, emit_lp, logP).

    beta(t,u) = log prob of completing the alignment from node (t,u);
    terminal: beta contribution 0 past the final blank at (tl-1, ul).
    Same associative-scan u-solver as the alpha pass, run in reverse."""
    lp = jax.nn.log_softmax(logits, axis=-1)
    B, T, U1, V = lp.shape
    U = U1 - 1
    blank_lp = lp[..., blank]
    lab = labels.astype(jnp.int32)
    emit_lp = jnp.take_along_axis(
        lp[:, :, :U, :], lab[:, None, :, None], axis=-1)[..., 0]
    NEG = -1e30
    tl = logit_lengths.astype(jnp.int32)
    ul = label_lengths.astype(jnp.int32)
    uu = jnp.arange(U1)
    # emission beyond the label length is illegal
    emit_pad = jnp.concatenate([emit_lp, jnp.full((B, T, 1), NEG)], -1)
    emit_pad = jnp.where(uu[None, None, :] < ul[:, None, None],
                         emit_pad, NEG)                        # [B, T, U+1]

    def solve_row(base, c):
        cs = jnp.concatenate([jnp.full(c.shape[:-1] + (1,), NEG), c[..., :-1]],
                             axis=-1)

        def comb(l, r):
            cl, bl = l
            cr, br = r
            return cl + cr, jnp.logaddexp(br, cr + bl)

        _, y = jax.lax.associative_scan(comb, (cs, base), axis=-1)
        return y

    def astep(alpha_prev, t):
        init0 = jnp.concatenate(
            [jnp.zeros((B, 1)), jnp.full((B, U), NEG)], -1)
        base = jnp.where(t == 0, init0,
                         alpha_prev + blank_lp[:, jnp.maximum(t - 1, 0), :])
        alpha = solve_row(base, emit_pad[:, t, :])
        return alpha, alpha

    _, alphas = jax.lax.scan(astep, jnp.full((B, U1), NEG), jnp.arange(T))
    alphas = jnp.moveaxis(alphas, 0, 1)                        # [B, T, U+1]

    def bstep(beta_next, t):
        # T-direction continuation: masked outside t+1 < tl; the final
        # blank at (tl-1, ul) terminates with continuation 0
        cont = jnp.where((t + 1 < tl)[:, None], beta_next, NEG)
        cont = jnp.where(((t == tl - 1)[:, None])
                         & (uu[None, :] == ul[:, None]), 0.0, cont)
        base = blank_lp[:, t, :] + cont
        # u-direction runs high->low: solve on the reversed axis. The
        # solver couples y[i] to y[i-1] with coefficient c[i-1]; for
        # beta(u) = logaddexp(base, beta(u+1) + emit(t, u)) the coefficient
        # is TARGET-indexed, so shift the reversed emission row left.
        er = emit_pad[:, t, ::-1]
        c = jnp.concatenate([er[:, 1:], jnp.full((B, 1), NEG)], -1)
        beta = solve_row(base[..., ::-1], c)[..., ::-1]
        return beta, beta

    _, betas = jax.lax.scan(bstep, jnp.full((B, U1), NEG),
                            jnp.arange(T - 1, -1, -1))
    betas = jnp.moveaxis(betas, 0, 1)[:, ::-1, :]              # [B, T, U+1]
    logP = betas[:, 0, 0]
    return -logP, alphas, betas, blank_lp, emit_pad, lp, logP


def _rnnt_loss_fastemit(logits, labels, logit_lengths, label_lengths,
                        blank, lam):
    @jax.custom_vjp
    def core(z):
        return _rnnt_alpha_beta(z, labels, logit_lengths, label_lengths,
                                blank)[0]

    def fwd(z):
        loss, alphas, betas, blank_lp, emit_pad, lp, logP = _rnnt_alpha_beta(
            z, labels, logit_lengths, label_lengths, blank)
        return loss, (z, alphas, betas, blank_lp, emit_pad, lp, logP)

    def bwd(res, g):
        z, alphas, betas, blank_lp, emit_pad, lp, logP = res
        B, T, U1, V = lp.shape
        tl = logit_lengths.astype(jnp.int32)
        ul = label_lengths.astype(jnp.int32)
        uu = jnp.arange(U1)
        NEG = -1e30
        # blank continuation mirrors the beta T-step (0 at the terminal)
        cont = jnp.where((jnp.arange(T)[None, :, None] + 1 < tl[:, None, None]),
                         jnp.concatenate([betas[:, 1:, :],
                                          jnp.full((B, 1, U1), NEG)], 1),
                         NEG)
        cont = jnp.where((jnp.arange(T)[None, :, None] == (tl - 1)[:, None, None])
                         & (uu[None, None, :] == ul[:, None, None]), 0.0, cont)
        gamma_blank = jnp.exp(alphas + blank_lp + cont - logP[:, None, None])
        beta_up = jnp.concatenate([betas[:, :, 1:],
                                   jnp.full((B, T, 1), NEG)], -1)
        gamma_emit = (1.0 + lam) * jnp.exp(
            alphas + emit_pad + beta_up - logP[:, None, None])
        occupancy = gamma_blank + gamma_emit                   # [B, T, U+1]
        grad_lp = jnp.zeros_like(lp)
        grad_lp = grad_lp.at[..., blank].add(-gamma_blank)
        lab = labels.astype(jnp.int32)
        lab_pad = jnp.concatenate(
            [lab, jnp.zeros((B, 1), jnp.int32)], -1)           # [B, U+1]
        bi = jnp.arange(B)[:, None, None]
        ti = jnp.arange(T)[None, :, None]
        grad_lp = grad_lp.at[
            bi, ti, uu[None, None, :],
            jnp.broadcast_to(lab_pad[:, None, :], (B, T, U1))].add(-gamma_emit)
        # d loss / d z through log_softmax: dz = dlp - softmax * sum(dlp)
        dz = grad_lp - jnp.exp(lp) * jnp.sum(grad_lp, -1, keepdims=True)
        return (dz * g[:, None, None, None],)

    core.defvjp(fwd, bwd)
    return core(logits)


def class_center_sample(label, num_classes, num_samples, seed=None):
    """phi class_center_sample: keep all positive classes + uniformly sampled
    negatives up to num_samples; remap labels into the sampled set."""
    from ...core.random import next_key

    lab = label.astype(jnp.int32)
    pos = jnp.zeros((num_classes,), bool).at[lab].set(True)
    # rank positives first (stable), then randomly-permuted negatives
    key = next_key()
    noise = jax.random.uniform(key, (num_classes,))
    score = jnp.where(pos, 2.0, noise)  # positives sort first
    order = jnp.argsort(-score)
    sampled = jnp.sort(order[:num_samples])
    # remap: position of each label inside the (sorted) sampled set
    inv = jnp.full((num_classes,), -1, jnp.int32).at[sampled].set(
        jnp.arange(num_samples, dtype=jnp.int32))
    return inv[lab], sampled


# phi reference names
warpctc = ctc_loss
warprnnt = rnnt_loss
