"""Quantized matmul kernels (reference: phi weight_only_linear / matmul_int8 /
llm_int8_matmul, paddle/phi/kernels/fusion/cutlass_*).

TPU design: int8 weights live in HBM at 1 byte/param; lax.dot_general with
preferred_element_type=int32 runs on the MXU's int8 path where available and
dequantization fuses into the epilogue. Per-channel scales follow the
reference's weight-only scheme (absmax over the input dim).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def quantize_weight_absmax(w, axis=0):
    """-> (int8 weight, fp scales) with per-output-channel absmax scaling.
    w: [in, out] (paddle linear layout); scales: [out]."""
    absmax = jnp.max(jnp.abs(w), axis=axis, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, jnp.squeeze(scale, axis)


def dequantize_weight(qweight, scales, dtype=jnp.float32):
    """Scale-folded dequantization: fp [in, out] table from the int8 weight +
    per-output-channel scales. This is weight_only_matmul's epilogue hoisted
    out of the hot path: on backends with no int8 GEMM (XLA:CPU) the per-call
    convert MATERIALIZES a full fp copy of the weight every decode step, which
    measured 1.6-1.7x slower than the fp GEMM it was supposed to beat
    (DECODEBENCH_r05: int8 299 vs fp 416 tok/s). Dequantizing once and reusing
    the fp table makes int8 decode run the identical GEMM as fp."""
    return qweight.astype(dtype) * scales.astype(dtype)


def weight_only_matmul(x, qweight, scales, bias=None, dequant=None):
    """phi weight_only_linear: fp activations x int8 weights. x: [..., in],
    qweight: [in, out] int8.

    Two epilogue structures, chosen by the caller per backend:
      * dequant=None — dequantize into the matmul epilogue (int8 stream from
        HBM, convert fused into the MXU feed): the TPU path, where 4x less
        weight traffic is the decode-phase win.
      * dequant=<fp table> — the hoisted form (dequantize_weight, computed
        ONCE): the CPU path, where XLA has no int8 GEMM and the per-call
        convert is pure overhead. Scales are folded into the table, so the
        hot loop is exactly the fp GEMM.
    """
    if dequant is not None:
        out = jnp.matmul(x, dequant.astype(x.dtype))
    else:
        out = jnp.matmul(x, qweight.astype(x.dtype)) * scales.astype(x.dtype)
    if bias is not None:
        out = out + bias
    return out


def matmul_int8(x, y, scale_x=1.0, scale_y=1.0):
    """phi matmul_int8: int8 x int8 -> int32 accumulate on the MXU, scaled
    back to fp32."""
    acc = lax.dot_general(
        x.astype(jnp.int8), y.astype(jnp.int8),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * (scale_x * scale_y)


def llm_int8_matmul(x, qweight, scales, threshold=6.0):
    """phi llm_int8_matmul (LLM.int8()): columns of x with outliers beyond
    `threshold` run in fp16/fp32; the rest run int8."""
    absx = jnp.max(jnp.abs(x), axis=tuple(range(x.ndim - 1)))
    outlier = absx > threshold                          # [in]
    x_reg = jnp.where(outlier[None, :], 0.0, x.reshape(-1, x.shape[-1]))
    x_out = jnp.where(outlier[None, :], x.reshape(-1, x.shape[-1]), 0.0)
    sx = jnp.maximum(jnp.max(jnp.abs(x_reg)), 1e-8) / 127.0
    xq = jnp.clip(jnp.round(x_reg / sx), -127, 127).astype(jnp.int8)
    reg = lax.dot_general(xq, qweight.astype(jnp.int8),
                          (((1,), (0,)), ((), ())),
                          preferred_element_type=jnp.int32)
    reg = reg.astype(jnp.float32) * (sx * scales.astype(jnp.float32))
    outl = jnp.matmul(x_out, qweight.astype(jnp.float32) * scales.astype(jnp.float32))
    out = reg + outl
    return out.reshape(x.shape[:-1] + (qweight.shape[1],))


# phi reference name
quant_for_compress = quantize_weight_absmax
