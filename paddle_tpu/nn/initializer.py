"""Weight initializers (reference: python/paddle/nn/initializer/*)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core import random as _random
from ..core.dtype import convert_dtype


def _fan_in_out(shape):
    shape = tuple(shape)
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv: [out, in, *k] -> receptive = prod(k)
    receptive = 1
    for s in shape[2:]:
        receptive *= s
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype="float32"):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        return jnp.full(tuple(shape), self.value, convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        key = _random.next_key()
        return self.mean + self.std * jax.random.normal(key, tuple(shape), convert_dtype(dtype))


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        key = _random.next_key()
        return self.mean + self.std * jax.random.truncated_normal(key, -2.0, 2.0, tuple(shape), convert_dtype(dtype))


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype="float32"):
        key = _random.next_key()
        return jax.random.uniform(key, tuple(shape), convert_dtype(dtype), self.low, self.high)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        key = _random.next_key()
        return jax.random.uniform(key, tuple(shape), convert_dtype(dtype), -limit, limit)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        key = _random.next_key()
        return std * jax.random.normal(key, tuple(shape), convert_dtype(dtype))


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        limit = gain * math.sqrt(3.0 / fi)
        key = _random.next_key()
        return jax.random.uniform(key, tuple(shape), convert_dtype(dtype), -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        std = gain / math.sqrt(fi)
        key = _random.next_key()
        return std * jax.random.normal(key, tuple(shape), convert_dtype(dtype))


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        import numpy as np

        arr = jnp.asarray(np.asarray(self.value)).astype(convert_dtype(dtype))
        assert tuple(arr.shape) == tuple(shape), f"{arr.shape} vs {shape}"
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype="float32"):
        key = _random.next_key()
        return self.gain * jax.nn.initializers.orthogonal()(key, tuple(shape), convert_dtype(dtype))


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype="float32"):
        return jax.nn.initializers.delta_orthogonal()(_random.next_key(), tuple(shape), convert_dtype(dtype))


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transposed convs (reference
    nn/initializer/Bilinear): weight[c_out, c_in, k, k] gets the separable
    triangle filter so a stride-s deconv starts as bilinear interpolation."""

    def __call__(self, shape, dtype="float32"):
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D conv weight")
        c_out, c_in, kh, kw = shape
        f_h, f_w = (kh + 1) // 2, (kw + 1) // 2
        ch = (kh - 1) / (2.0 * f_h) if kh % 2 == 0 else (kh - 1) / 2.0
        cw = (kw - 1) / (2.0 * f_w) if kw % 2 == 0 else (kw - 1) / 2.0
        og = np.ogrid[:kh, :kw]
        filt = (1 - abs(og[0] - ch) / f_h) * (1 - abs(og[1] - cw) / f_w)
        w = np.zeros(shape, np.dtype(dtype))
        for i in range(c_out):
            w[i, i % c_in] = filt
        return Tensor(jnp.asarray(w))


def calculate_gain(nonlinearity, param=None):
    """Recommended init gain per activation (reference
    nn/initializer/calculate_gain; the values are the published table)."""
    import math

    table = {
        "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0, "conv3d": 1.0,
        "conv_transpose1d": 1.0, "conv_transpose2d": 1.0,
        "conv_transpose3d": 1.0, "sigmoid": 1.0,
        "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
        "selu": 3.0 / 4.0,
    }
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else float(param)
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity not in table:
        raise ValueError(f"unsupported nonlinearity {nonlinearity!r}")
    return table[nonlinearity]


_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    """Default initializers for subsequently created parameters (reference
    nn/initializer/set_global_initializer); Layer.create_parameter reads
    these when no explicit initializer is given. Pass None to reset."""
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init
