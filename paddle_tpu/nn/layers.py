"""Basic layers (reference: python/paddle/nn/layer/{common,conv,pooling}.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops import api
from . import functional as F
from . import initializer as I
from .layer import Layer, Parameter


class Linear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=None if weight_attr is None or getattr(weight_attr, "initializer", None) is None else weight_attr.initializer,
        )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([out_features], attr=None if bias_attr in (None, True) else bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None, sparse=False, weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0) if weight_attr is None else None,
        )

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx)

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, training=self.training, mode=self.mode, axis=self.axis)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training, data_format=self.data_format)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        return api.flatten(x, self.start_axis, self.stop_axis)


class Identity(Layer):
    def forward(self, x):
        return x


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest", align_corners=False, data_format="NCHW"):
        super().__init__()
        self.size, self.scale_factor, self.mode = size, scale_factor, mode
        self.align_corners, self.data_format = align_corners, data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode, self.align_corners, self.data_format)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW"):
        super().__init__()
        self.padding, self.mode, self.value, self.data_format = padding, mode, value, data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW"):
        super().__init__()
        self.upscale_factor = upscale_factor

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor)


# --- conv ------------------------------------------------------------------
class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride, padding, dilation,
                 groups, weight_attr, bias_attr, data_format, ndim):
        super().__init__()
        self.in_channels = in_channels
        self.out_channels = out_channels
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) else (kernel_size,) * ndim
        self._kernel_size = tuple(ks)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        fan_in = in_channels // groups
        for k in self._kernel_size:
            fan_in *= k
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, *self._kernel_size], attr=weight_attr,
            default_initializer=I.KaimingUniform(fan_in=fan_in) if weight_attr is None else None,
        )
        if bias_attr is False:
            self.bias = None
        else:
            bound = 1.0 / (fan_in ** 0.5)
            self.bias = self.create_parameter(
                [out_channels], attr=None if bias_attr in (None, True) else bias_attr,
                is_bias=True, default_initializer=I.Uniform(-bound, bound),
            )


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, weight_attr, bias_attr, data_format, 2)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)

    def extra_repr(self):
        return (f"{self.in_channels}, {self.out_channels}, kernel_size={self._kernel_size}, "
                f"stride={self._stride}, padding={self._padding}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, weight_attr, bias_attr, data_format, 1)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) else (kernel_size,) * 2
        self._attrs = (stride, padding, output_padding, dilation, groups, data_format)
        self.weight = self.create_parameter(
            [in_channels, out_channels // groups, *ks], attr=weight_attr,
        )
        self.bias = None if bias_attr is False else self.create_parameter([out_channels], is_bias=True)

    def forward(self, x):
        s, p, op, d, g, df = self._attrs
        return F.conv2d_transpose(x, self.weight, self.bias, s, p, op, d, g, df)


# --- pooling ---------------------------------------------------------------
class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCHW"):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, data_format)

    def forward(self, x):
        k, s, p, c, df = self.args
        return F.max_pool2d(x, k, s, p, c, df)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, data_format="NCHW"):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, exclusive, data_format)

    def forward(self, x):
        k, s, p, c, e, df = self.args
        return F.avg_pool2d(x, k, s, p, c, e, df)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW"):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW"):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)


# --- activations as layers --------------------------------------------------
def _act_layer(name, fn_name=None):
    fn = getattr(F, fn_name or name.lower())

    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._args = args
            self._kwargs = kwargs

        def forward(self, x):
            return fn(x, *self._args, **self._kwargs)

    _Act.__name__ = name
    return _Act


ReLU = _act_layer("ReLU", "relu")
ReLU6 = _act_layer("ReLU6", "relu6")
GELU = _act_layer("GELU", "gelu")
Sigmoid = _act_layer("Sigmoid", "sigmoid")
LogSigmoid = _act_layer("LogSigmoid", "log_sigmoid")
Silu = _act_layer("Silu", "silu")
Swish = _act_layer("Swish", "swish")
Mish = _act_layer("Mish", "mish")
LeakyReLU = _act_layer("LeakyReLU", "leaky_relu")
ELU = _act_layer("ELU", "elu")
SELU = _act_layer("SELU", "selu")
CELU = _act_layer("CELU", "celu")
Softplus = _act_layer("Softplus", "softplus")
Softshrink = _act_layer("Softshrink", "softshrink")
Hardshrink = _act_layer("Hardshrink", "hardshrink")
Hardtanh = _act_layer("Hardtanh", "hardtanh")
Hardsigmoid = _act_layer("Hardsigmoid", "hardsigmoid")
Hardswish = _act_layer("Hardswish", "hardswish")
Tanhshrink = _act_layer("Tanhshrink", "tanhshrink")
ThresholdedReLU = _act_layer("ThresholdedReLU", "thresholded_relu")
Softmax = _act_layer("Softmax", "softmax")
LogSoftmax = _act_layer("LogSoftmax", "log_softmax")
Maxout = _act_layer("Maxout", "maxout")
GLU = _act_layer("GLU", "glu")


class Tanh(Layer):
    def forward(self, x):
        return api.tanh(x)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None, data_format="NCHW"):
        super().__init__()
        self.weight = self.create_parameter([num_parameters], default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight)


# --- round-3 conv/pool layers (reference: nn/layer/conv.py:899 Conv3D,
# nn/layer/pooling.py 1d/3d + adaptive + unpool variants) -------------------
class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, weight_attr, bias_attr, data_format, 3)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class _ConvTransposeNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride, padding,
                 output_padding, dilation, groups, weight_attr, bias_attr, ndim, fn):
        super().__init__()
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) else (kernel_size,) * ndim
        self._attrs = (stride, padding, output_padding, dilation, groups)
        self._fn = fn
        self.weight = self.create_parameter(
            [in_channels, out_channels // groups, *ks], attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], is_bias=True)

    def forward(self, x):
        s, p, op, d, g = self._attrs
        return self._fn(x, self.weight, self.bias, s, p, op, d, g)


class Conv1DTranspose(_ConvTransposeNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride, padding,
                         output_padding, dilation, groups, weight_attr, bias_attr,
                         1, F.conv1d_transpose)


class Conv3DTranspose(_ConvTransposeNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride, padding,
                         output_padding, dilation, groups, weight_attr, bias_attr,
                         3, F.conv3d_transpose)


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False):
        super().__init__()
        self.args = (kernel_size, stride, padding, return_mask, ceil_mode)

    def forward(self, x):
        return F.max_pool1d(x, *self.args)


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False):
        super().__init__()
        self.args = (kernel_size, stride, padding, exclusive, ceil_mode)

    def forward(self, x):
        return F.avg_pool1d(x, *self.args)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCDHW"):
        super().__init__()
        self.args = (kernel_size, stride, padding, return_mask, ceil_mode, data_format)

    def forward(self, x):
        return F.max_pool3d(x, *self.args)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, data_format="NCDHW"):
        super().__init__()
        self.args = (kernel_size, stride, padding, exclusive, ceil_mode, data_format)

    def forward(self, x):
        return F.avg_pool3d(x, *self.args)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False):
        super().__init__()
        if return_mask:
            raise NotImplementedError(
                "AdaptiveMaxPool1D(return_mask=True) is not supported")
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW"):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, data_format="NCDHW"):
        super().__init__()
        if return_mask:
            raise NotImplementedError(
                "AdaptiveMaxPool3D(return_mask=True) is not supported")
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCL"):
        super().__init__()
        self.args = (kernel_size, stride, padding)

    def forward(self, x, indices, output_size=None):
        k, s, p = self.args
        return F.max_unpool1d(x, indices, k, s, p, output_size)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW"):
        super().__init__()
        self.args = (kernel_size, stride, padding)

    def forward(self, x, indices, output_size=None):
        k, s, p = self.args
        return F.max_unpool2d(x, indices, k, s, p, output_size)


class AlphaDropout(Layer):
    def __init__(self, p=0.5):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW"):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training)


class ZeroPad2D(Layer):
    def __init__(self, padding, data_format="NCHW"):
        super().__init__()
        self.padding = padding

    def forward(self, x):
        return F.zeropad2d(x, self.padding)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW"):
        super().__init__()
        self.args = (size, alpha, beta, k)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW"):
        super().__init__()
        self.groups = groups

    def forward(self, x):
        return F.channel_shuffle(x, self.groups)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW"):
        super().__init__()
        self.factor = downscale_factor

    def forward(self, x):
        return F.pixel_unshuffle(x, self.factor)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
        super().__init__()
        self.args = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, *self.args)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


# -- round-5 API-parity layers (reference python/paddle/nn/layer/) ----------

Softsign = _act_layer("Softsign", "softsign")
RReLU = _act_layer("RReLU", "rrelu")


class Softmax2D(Layer):
    """Softmax over the channel axis of NCHW input (reference
    nn/layer/activation.py Softmax2D)."""

    def forward(self, x):
        if x.ndim not in (3, 4):
            raise ValueError(f"Softmax2D expects 3D/4D input, got {x.ndim}D")
        return F.softmax(x, axis=-3)


class UpsamplingNearest2D(Layer):
    """Reference nn/layer/common.py UpsamplingNearest2D."""

    def __init__(self, size=None, scale_factor=None, data_format="NCHW"):
        super().__init__()
        self.size, self.scale_factor, self.data_format = \
            size, scale_factor, data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, "nearest",
                             False, self.data_format)


class UpsamplingBilinear2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW"):
        super().__init__()
        self.size, self.scale_factor, self.data_format = \
            size, scale_factor, data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, "bilinear",
                             True, self.data_format)


class Pad1D(Layer):
    """Reference nn/layer/common.py Pad1D over NCL input (an int padding
    means the same pad on both ends, as in the reference)."""

    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL"):
        super().__init__()
        self.padding = [padding] * 2 if isinstance(padding, int) else padding
        self.mode, self.value, self.data_format = mode, value, data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad3D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW"):
        super().__init__()
        self.padding = [padding] * 6 if isinstance(padding, int) else padding
        self.mode, self.value, self.data_format = mode, value, data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class Bilinear(Layer):
    """out = x1 @ W[o] @ x2 + b (reference nn/layer/common.py Bilinear)."""

    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features])
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_features], is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW"):
        super().__init__()
        self.args = (kernel_size, stride, padding)

    def forward(self, x, indices, output_size=None):
        k, s, p = self.args
        return F.max_unpool3d(x, indices, k, s, p, output_size)


class Unflatten(Layer):
    """Reference nn/layer/common.py Unflatten: expand one axis to `shape`."""

    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, shape

    def forward(self, x):
        return api.unflatten(x, self.axis, self.shape)
