"""paddle.nn.quant (reference python/paddle/nn/quant/): the Stub layer —
a placeholder that QAT replaces with a quanter observer in-place."""
from __future__ import annotations

from .layer import Layer

__all__ = ["Stub"]


class Stub(Layer):
    """Quantization stub (reference nn/quant/stub.py Stub): identity until
    the QAT pass swaps in the configured fake-quant observer."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, x):
        if self._observer is not None:
            return self._observer(x)
        return x
