"""Transformer layers (reference: python/paddle/nn/layer/transformer.py; the
fused GPU paths in paddle/fluid/operators/fused/ map to the attention op which
lowers to Pallas flash attention on TPU)."""
from __future__ import annotations

from ..ops import api
from . import functional as F
from .container import LayerList
from .layer import Layer
from .layers import Dropout, Linear
from .norm import LayerNorm


class MultiHeadAttention(Layer):
    import collections as _collections

    Cache = _collections.namedtuple("Cache", ["k", "v"])
    StaticCache = _collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = query if value is None else value
        b, sq, _ = query.shape
        q = api.reshape(self.q_proj(query), [b, sq, self.num_heads, self.head_dim])
        k = api.reshape(self.k_proj(key), [b, key.shape[1], self.num_heads, self.head_dim])
        v = api.reshape(self.v_proj(value), [b, value.shape[1], self.num_heads, self.head_dim])
        if cache is not None:
            k = api.concat([cache[0], k], axis=1)
            v = api.concat([cache[1], v], axis=1)
        weights = None
        if self.need_weights:
            # explicit-softmax path: the fused SDPA never materializes the
            # probability tensor the (out, weights) contract returns
            import math

            scores = api.scale(
                api.matmul(api.transpose(q, [0, 2, 1, 3]),
                           api.transpose(k, [0, 2, 1, 3]),
                           transpose_y=True),
                1.0 / math.sqrt(self.head_dim))
            if attn_mask is not None:
                scores = api.add(scores, attn_mask)
            weights = api.softmax(scores, axis=-1)
            out = api.transpose(api.matmul(weights, api.transpose(
                v, [0, 2, 1, 3])), [0, 2, 1, 3])
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask,
                dropout_p=self.dropout if self.training else 0.0,
                training=self.training,
            )
        out = api.reshape(out, [b, sq, self.embed_dim])
        out = self.out_proj(out)
        outs = (out,)
        if self.need_weights:
            outs = outs + (weights,)
        if cache is not None:
            outs = outs + (self.Cache(k, v),)
        return outs[0] if len(outs) == 1 else outs

    def gen_cache(self, key, value=None, type=None):  # noqa: A002
        """Empty incremental-decode cache (reference MHA.gen_cache): k/v
        grow by concat on each cached forward."""
        import jax.numpy as jnp

        from ..core.tensor import Tensor

        b = key.shape[0]
        empty = Tensor(jnp.zeros((b, 0, self.num_heads, self.head_dim),
                                 jnp.float32))
        return self.Cache(empty, empty)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1, activation="relu",
                 attn_dropout=None, act_dropout=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout,
                                            weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        src = self.self_attn(src, attn_mask=src_mask)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.act_dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src


class TransformerEncoder(Layer):
    """enable_recompute activates per-layer activation checkpointing
    (reference: fleet recompute wiring in TransformerEncoder)."""

    def __init__(self, encoder_layer, num_layers, norm=None, enable_recompute=False):
        super().__init__()
        import copy

        self.layers = LayerList([encoder_layer] + [copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm
        self.enable_recompute = enable_recompute

    def forward(self, src, src_mask=None):
        out = src
        for layer in self.layers:
            if self.enable_recompute and self.training:
                from ..distributed.fleet.recompute import recompute

                out = recompute(layer, out, src_mask=src_mask)
            else:
                out = layer(out, src_mask=src_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1, activation="relu",
                 attn_dropout=None, act_dropout=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout)
        self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        new_cache = None
        if cache is not None:
            tgt, new_cache = self.self_attn(tgt, attn_mask=tgt_mask,
                                            cache=cache)
        else:
            tgt = self.self_attn(tgt, attn_mask=tgt_mask)
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt = self.cross_attn(tgt, memory, memory, attn_mask=memory_mask)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.act_dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        if new_cache is not None:
            return tgt, new_cache
        return tgt

    def gen_cache(self, memory):
        return self.self_attn.gen_cache(memory)


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([decoder_layer] + [copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        out = tgt
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is not None:
                out, nc = layer(out, memory, tgt_mask=tgt_mask,
                                memory_mask=memory_mask, cache=cache[i])
                new_caches.append(nc)
            else:
                out = layer(out, memory, tgt_mask=tgt_mask,
                            memory_mask=memory_mask)
        if self.norm is not None:
            out = self.norm(out)
        if cache is not None:
            return out, new_caches
        return out

    def gen_cache(self, memory, do_zip=False):
        caches = [layer.gen_cache(memory) for layer in self.layers]
        return list(zip(*caches)) if do_zip else caches


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6, num_decoder_layers=6,
                 dim_feedforward=2048, dropout=0.1, activation="relu",
                 attn_dropout=None, act_dropout=None, normalize_before=False,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        self.d_model = d_model
        self.nhead = nhead
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(d_model, nhead, dim_feedforward, dropout,
                                                activation, attn_dropout, act_dropout, normalize_before)
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              LayerNorm(d_model))
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(d_model, nhead, dim_feedforward, dropout,
                                                activation, attn_dropout, act_dropout, normalize_before)
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              LayerNorm(d_model))

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask, memory_mask=memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        import jax.numpy as jnp

        from ..core.tensor import Tensor

        mask = jnp.where(jnp.tril(jnp.ones((length, length), bool)), 0.0, float("-inf"))
        return Tensor(mask)
