"""Gradient clipping (reference: python/paddle/nn/clip.py —
ClipGradByGlobalNorm/Norm/Value; the TP-aware hybrid version lives in
distributed/fleet)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            gv = g._value if isinstance(g, Tensor) else g
            out.append((p, Tensor(jnp.clip(gv, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            gv = g._value if isinstance(g, Tensor) else g
            norm = jnp.sqrt(jnp.sum(jnp.square(gv.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((gv.astype(jnp.float32) * scale).astype(gv.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        sq = 0.0
        for _, g in params_grads:
            gv = g._value if isinstance(g, Tensor) else g
            sq = sq + jnp.sum(jnp.square(gv.astype(jnp.float32)))
        global_norm = jnp.sqrt(sq)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            gv = g._value if isinstance(g, Tensor) else g
            out.append((p, Tensor((gv.astype(jnp.float32) * scale).astype(gv.dtype))))
        return out

    def functional_clip(self, g_vals):
        """Pure-array form for compiled steps."""
        sq = 0.0
        for g in g_vals:
            sq = sq + jnp.sum(jnp.square(g.astype(jnp.float32)))
        global_norm = jnp.sqrt(sq)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        return [(g.astype(jnp.float32) * scale).astype(g.dtype) for g in g_vals]
