"""paddle.nn.utils (reference python/paddle/nn/utils/): parametrization
hooks (weight/spectral norm) and parameter-vector/grad utilities."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops import api
from .layer import Parameter

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters",
           "clip_grad_norm_", "clip_grad_value_"]


def _norm_except(v, dim):
    """||v|| reduced over every axis except `dim` (dim=None: full norm)."""
    if dim is None:
        return jnp.sqrt(jnp.sum(jnp.square(v)))
    axes = tuple(a for a in range(v.ndim) if a != dim)
    return jnp.sqrt(jnp.sum(jnp.square(v), axis=axes, keepdims=True))


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize layer.<name> as g * v/||v|| (reference
    nn/utils/weight_norm_hook.py). v and g become the trainable params;
    a pre-forward hook recomputes the weight each call."""
    w = getattr(layer, name)
    v = Parameter(w._value)
    g = Parameter(_norm_except(w._value, dim))
    setattr(layer, name + "_v", v)
    setattr(layer, name + "_g", g)
    # the original weight is no longer a trainable parameter
    if name in layer._parameters:
        del layer._parameters[name]

    def _recompute(lyr, inputs):
        norm = _norm_except(v._value, dim)
        lyr.__dict__[name] = Tensor(
            g._value * v._value / jnp.maximum(norm, 1e-12))
        return inputs

    handle = layer.register_forward_pre_hook(_recompute)
    layer.__dict__[name + "_wn_hook"] = handle
    layer.__dict__[name + "_wn_dim"] = dim
    _recompute(layer, ())
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold g*v/||v|| back into a plain parameter and drop the hook."""
    v = getattr(layer, name + "_v")
    g = getattr(layer, name + "_g")
    hook = layer.__dict__.pop(name + "_wn_hook", None)
    if hook is not None:
        hook.remove()
    # fold back along the SAME dim the hook normalized over
    dim = layer.__dict__.pop(name + "_wn_dim", 0)
    dimless = g._value.ndim == 0
    norm = _norm_except(v._value, None if dimless else dim)
    w = Parameter(g._value * v._value / jnp.maximum(norm, 1e-12))
    for suffix in ("_v", "_g"):
        layer._parameters.pop(name + suffix, None)
    layer.__dict__.pop(name, None)
    setattr(layer, name, w)
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Divide layer.<name> by its spectral norm each forward (reference
    nn/utils/spectral_norm_hook.py), persisting the power-iteration
    vectors as buffers."""
    w = getattr(layer, name)
    if dim is None:
        dim = 0
    h = w.shape[dim]
    width = int(np.prod([s for i, s in enumerate(w.shape) if i != dim]))
    rng = np.random.RandomState(0)

    def unit(n):
        x = rng.normal(size=n).astype(np.float32)
        return x / max(float(np.linalg.norm(x)), eps)

    u = Tensor(jnp.asarray(unit(h)))
    vv = Tensor(jnp.asarray(unit(width)))
    orig = Parameter(w._value)
    setattr(layer, name + "_orig", orig)
    if name in layer._parameters:
        del layer._parameters[name]

    def _recompute(lyr, inputs):
        out = api.spectral_norm(orig, u, vv, dim, n_power_iterations, eps)
        lyr.__dict__[name] = out
        return inputs

    handle = layer.register_forward_pre_hook(_recompute)
    layer.__dict__[name + "_sn_hook"] = handle
    _recompute(layer, ())
    return layer


def parameters_to_vector(parameters, name=None):
    ps = list(parameters)
    return Tensor(jnp.concatenate([p._value.reshape(-1) for p in ps]))


def vector_to_parameters(vec, parameters, name=None):
    v = vec._value if isinstance(vec, Tensor) else jnp.asarray(vec)
    off = 0
    for p in parameters:
        n = int(np.prod(p.shape))
        p._value = v[off:off + n].reshape(tuple(p.shape)).astype(
            p._value.dtype)
        off += n


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """In-place global-norm gradient clip over .grad (reference
    nn/utils/clip_grad_norm_); returns the total norm."""
    ps = [p for p in parameters if p.grad is not None]
    if not ps:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(p.grad._value)) for p in ps]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(p.grad._value) ** norm_type) for p in ps])
        ) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError("non-finite gradient norm in clip_grad_norm_")
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-12), 1.0)
    for p in ps:
        p.grad._value = p.grad._value * scale.astype(p.grad._value.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    for p in parameters:
        if p.grad is not None:
            p.grad._value = jnp.clip(p.grad._value, -clip_value, clip_value)
