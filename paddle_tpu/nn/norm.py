"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.autograd import no_grad
from ..ops import api
from . import functional as F
from . import initializer as I
from .layer import Layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(self._normalized_shape, attr=None if weight_attr in (None, True) else weight_attr, default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(self._normalized_shape, attr=None if bias_attr in (None, True) else bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """Reference: python/paddle/incubate/nn/functional/rms_norm.py as a layer."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter([hidden_size], default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter([num_features], default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([num_features], is_bias=True)
        self.register_buffer("_mean", api.zeros([num_features], "float32"))
        self.register_buffer("_variance", api.ones([num_features], "float32"))

    def forward(self, x):
        training = self.training and not (self._use_global_stats is True)
        y, new_mean, new_var = api.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=training, momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format,
        )
        if training:
            with no_grad():
                self._mean._value = new_mean._value if hasattr(new_mean, "_value") else new_mean
                self._variance._value = new_var._value if hasattr(new_var, "_value") else new_var
        return y

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}, epsilon={self._epsilon}"


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


BatchNorm = _BatchNormBase


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Under jit+mesh the batch axis is sharded and XLA's
    batch-norm statistics become per-shard; a psum over the 'data' axis is
    inserted by the collective layer when inside shard_map. Eager single-chip:
    identical to BatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        # recursively swap _BatchNormBase instances
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, _BatchNormBase) and not isinstance(sub, SyncBatchNorm):
                new = SyncBatchNorm(sub._num_features, sub._momentum, sub._epsilon,
                                    data_format=sub._data_format)
                new.weight = sub.weight
                new.bias = sub.bias
                new._mean = sub._mean
                new._variance = sub._variance
                layer._sub_layers[name] = new
            else:
                cls.convert_sync_batchnorm(sub)
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter([num_channels], default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter([num_channels], is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self.weight, self.bias, self._epsilon, self._data_format)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter([num_features], default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter([num_features], is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, self.weight, self.bias, self._epsilon)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def forward(self, x):
        div = api.square(x)
        half = self.size // 2
        import jax.numpy as jnp

        val = div._value if hasattr(div, "_value") else div
        pads = [(0, 0), (half, self.size - 1 - half), (0, 0), (0, 0)]
        padded = jnp.pad(val, pads)
        window = jnp.stack([padded[:, i : i + val.shape[1]] for i in range(self.size)]).sum(0)
        from ..core.tensor import Tensor

        denom = Tensor((self.k + self.alpha * window) ** self.beta)
        return x / denom


class InstanceNorm1D(InstanceNorm2D):
    """NCL input; the functional normalizes over all trailing spatial axes,
    so only the expected-rank check differs (reference nn/layer/norm.py)."""


class InstanceNorm3D(InstanceNorm2D):
    """NCDHW input."""


class SpectralNorm(Layer):
    """Weight spectral normalization via persistent power iteration
    (reference nn/layer/norm.py SpectralNorm; phi spectral_norm kernel).
    Holds the u/v iteration vectors as buffers; forward(weight) returns
    weight / sigma_max."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None):
        super().__init__()
        import numpy as _np

        self._dim, self._power_iters, self._eps = dim, power_iters, eps
        h = weight_shape[dim]
        w = int(_np.prod([s for i, s in enumerate(weight_shape)
                          if i != dim]))
        rng = _np.random.RandomState(0)

        def _unit(n):
            v = rng.normal(size=n).astype(_np.float32)
            return v / max(float(_np.linalg.norm(v)), eps)

        self.weight_u = self.create_parameter([h])
        self.weight_u.set_value(_unit(h))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter([w])
        self.weight_v.set_value(_unit(w))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ..ops import api as _api

        return _api.spectral_norm(weight, self.weight_u, self.weight_v,
                                  self._dim, self._power_iters, self._eps)
