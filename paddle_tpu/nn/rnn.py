"""Recurrent layers: cells, RNN/BiRNN wrappers, SimpleRNN/LSTM/GRU.

Reference: python/paddle/nn/layer/rnn.py (RNNCellBase:80, SimpleRNNCell:1613?
— cell classes, RNN:1171, BiRNN:1285, RNNBase:1417, SimpleRNN:1613,
LSTM:1735, GRU:1861).

TPU-native: the multi-layer classes lower to the single fused `rnn` op
(ops/kernels/rnn.py) whose time loop is lax.scan — one compiled program per
shape, backward via the registry's vjp path. The generic RNN/BiRNN wrappers
(arbitrary user cells) unroll in Python like the reference's dygraph path.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as np

from ..core.tensor import Tensor
from ..ops import api
from . import initializer as I
from .layer import Layer, Parameter

__all__ = [
    "RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell",
    "RNN", "BiRNN", "SimpleRNN", "LSTM", "GRU",
]


def _uniform_init(shape, dtype, bound):
    return I.Uniform(-bound, bound)(shape, dtype)


class RNNCellBase(Layer):
    """Base for single-step cells (reference RNNCellBase)."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        shape = shape or self.state_shape
        if isinstance(shape, (list, tuple)) and shape and isinstance(shape[0], (list, tuple)):
            return tuple(
                api.full([batch] + list(s), init_value, dtype=dtype or "float32")
                for s in shape)
        return api.full([batch] + list(shape), init_value, dtype=dtype or "float32")


class _GateCell(RNNCellBase):
    """Shared parameter layout for the builtin cells: weight_ih [kH, D],
    weight_hh [kH, H], bias_ih/bias_hh [kH] with U(-1/sqrt(H), 1/sqrt(H))."""

    def __init__(self, input_size, hidden_size, k, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        bound = 1.0 / math.sqrt(hidden_size)
        mk = lambda shape: Parameter(_uniform_init(shape, "float32", bound))
        self.weight_ih = mk([k * hidden_size, input_size])
        self.weight_hh = mk([k * hidden_size, hidden_size])
        self.bias_ih = mk([k * hidden_size]) if bias_ih_attr is not False else None
        self.bias_hh = mk([k * hidden_size]) if bias_hh_attr is not False else None

    def _proj(self, x, h):
        g = api.matmul(x, self.weight_ih, transpose_y=True)
        if self.bias_ih is not None:
            g = g + self.bias_ih
        g2 = api.matmul(h, self.weight_hh, transpose_y=True)
        if self.bias_hh is not None:
            g2 = g2 + self.bias_hh
        return g + g2


class SimpleRNNCell(_GateCell):
    def __init__(self, input_size, hidden_size, activation="tanh", **kw):
        super().__init__(input_size, hidden_size, 1, **kw)
        if activation not in ("tanh", "relu"):
            raise ValueError("activation must be tanh or relu")
        self.activation = activation

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h = api.tanh(self._proj(inputs, states)) if self.activation == "tanh" \
            else api.relu(self._proj(inputs, states))
        return h, h


class LSTMCell(_GateCell):
    def __init__(self, input_size, hidden_size, **kw):
        super().__init__(input_size, hidden_size, 4, **kw)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h_prev, c_prev = states
        gates = self._proj(inputs, h_prev)
        i, f, g, o = api.split(gates, 4, axis=-1)
        i, f, o = api.sigmoid(i), api.sigmoid(f), api.sigmoid(o)
        c = f * c_prev + i * api.tanh(g)
        h = o * api.tanh(c)
        return h, (h, c)


class GRUCell(_GateCell):
    def __init__(self, input_size, hidden_size, **kw):
        super().__init__(input_size, hidden_size, 3, **kw)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h_prev = states
        x_g = api.matmul(inputs, self.weight_ih, transpose_y=True)
        if self.bias_ih is not None:
            x_g = x_g + self.bias_ih
        h_g = api.matmul(h_prev, self.weight_hh, transpose_y=True)
        if self.bias_hh is not None:
            h_g = h_g + self.bias_hh
        xr, xz, xc = api.split(x_g, 3, axis=-1)
        hr, hz, hc = api.split(h_g, 3, axis=-1)
        r = api.sigmoid(xr + hr)
        z = api.sigmoid(xz + hz)
        c = api.tanh(xc + r * hc)
        h = z * h_prev + (1.0 - z) * c
        return h, h


class RNN(Layer):
    """Scan an arbitrary cell over time (reference RNN:1171; dygraph unroll)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        import jax

        x = inputs if self.time_major else api.transpose(inputs, [1, 0, 2])
        T = x.shape[0]
        states = initial_states
        if states is None and sequence_length is not None:
            # materialize zeros so the masked update has a previous state
            if hasattr(self.cell, "get_initial_states"):
                states = self.cell.get_initial_states(x[0])
            else:
                _, states = self.cell(x[0] * 0.0, None)
                states = jax.tree_util.tree_map(
                    lambda s: s * 0.0, states,
                    is_leaf=lambda v: isinstance(v, Tensor))
        outs = []
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        for t in steps:
            out, new_states = self.cell(x[t], states)
            if sequence_length is not None:
                valid = api.unsqueeze(
                    api.cast(api.less_than(
                        api.full([1], t, dtype="int32"), sequence_length), "float32"),
                    -1)
                out = out * valid
                states = jax.tree_util.tree_map(
                    lambda n, o: n * valid + o * (1.0 - valid),
                    new_states, states,
                    is_leaf=lambda v: isinstance(v, Tensor))
            else:
                states = new_states
            outs.append(out)
        if self.is_reverse:
            outs.reverse()
        outputs = api.stack(outs, axis=0)
        if not self.time_major:
            outputs = api.transpose(outputs, [1, 0, 2])
        return outputs, states


class BiRNN(Layer):
    """Forward + backward cells, outputs concatenated (reference BiRNN:1285)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        st_fw, st_bw = (initial_states if initial_states is not None
                        else (None, None))
        out_fw, fin_fw = self.rnn_fw(inputs, st_fw, sequence_length)
        out_bw, fin_bw = self.rnn_bw(inputs, st_bw, sequence_length)
        outputs = api.concat([out_fw, out_bw], axis=-1)
        return outputs, (fin_fw, fin_bw)


class _RNNBase(Layer):
    """Multi-layer stack lowering to the fused rnn op (reference RNNBase:1417)."""

    _K = {"RNN_TANH": 1, "RNN_RELU": 1, "LSTM": 4, "GRU": 3}

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        super().__init__()
        if direction not in ("forward", "bidirect", "bidirectional"):
            raise ValueError(f"bad direction {direction}")
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.num_directions = 2 if direction != "forward" else 1
        k = self._K[mode]
        bound = 1.0 / math.sqrt(hidden_size)
        self._weight_names = []
        for layer in range(num_layers):
            in_size = input_size if layer == 0 else hidden_size * self.num_directions
            for d in range(self.num_directions):
                suffix = f"_l{layer}" + ("_reverse" if d == 1 else "")
                for wname, shape in (
                    (f"weight_ih{suffix}", [k * hidden_size, in_size]),
                    (f"weight_hh{suffix}", [k * hidden_size, hidden_size]),
                    (f"bias_ih{suffix}", [k * hidden_size]),
                    (f"bias_hh{suffix}", [k * hidden_size]),
                ):
                    p = Parameter(_uniform_init(shape, "float32", bound))
                    self.add_parameter(wname, p)
                    self._weight_names.append(wname)

    def _weights(self):
        return [getattr(self, n) for n in self._weight_names]

    def forward(self, inputs, initial_states=None, sequence_length=None):
        batch_idx = 1 if self.time_major else 0
        batch = inputs.shape[batch_idx]
        n = self.num_layers * self.num_directions
        if initial_states is None:
            h0 = api.zeros([n, batch, self.hidden_size], dtype="float32")
            initial_states = (h0, api.zeros_like(h0)) if self.mode == "LSTM" else h0
        mode_kernel = "LSTM" if self.mode == "LSTM" else (
            "GRU" if self.mode == "GRU" else "SimpleRNN")
        act = "relu" if self.mode == "RNN_RELU" else "tanh"
        states = initial_states if isinstance(initial_states, (tuple, list)) \
            else (initial_states,)
        result = api.rnn(
            inputs, tuple(states), self._weights(), mode=mode_kernel,
            num_layers=self.num_layers, direction=self.direction,
            time_major=self.time_major,
            dropout=self.dropout, training=self.training, activation=act,
            sequence_length=sequence_length)
        if self.mode == "LSTM":
            outputs, h_n, c_n = result
            return outputs, (h_n, c_n)
        outputs, h_n = result
        return outputs, h_n


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        mode = "RNN_RELU" if activation == "relu" else "RNN_TANH"
        super().__init__(mode, input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation, **kw)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)
