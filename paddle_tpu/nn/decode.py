"""Seq2seq decoding: BeamSearchDecoder + dynamic_decode.

Reference: python/paddle/nn/decode.py — Decoder protocol
(initialize/step/finalize), BeamSearchDecoder over any RNNCell-like
callable, and the dynamic_decode driver. Dygraph semantics here: a host
step loop (the reference's dygraph path is the same; its static path
builds a while_op); each step's tensor math is compiled by XLA as usual,
and the final backtrace is the registered gather_tree op
(phi/kernels/gather_tree_kernel)."""
from __future__ import annotations

import collections

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops import api


class Decoder:
    """Protocol: initialize() -> (inputs, states, finished);
    step(time, inputs, states) -> (outputs, states, inputs, finished);
    finalize(outputs, states, seq_lengths) -> (outputs, states)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam search over a cell: log-prob accumulation, per-step top-k over
    (beam x vocab), parent-pointer bookkeeping, end-token freezing.

    cell(inputs, states) must return (logits_or_hidden, next_states); pass
    output_fn to map cell output to vocab logits and embedding_fn to map
    token ids to the next step's inputs (reference BeamSearchDecoder
    signature)."""

    OutputWrapper = collections.namedtuple(
        "OutputWrapper", ("scores", "predicted_ids", "parent_ids"))
    StateWrapper = collections.namedtuple(
        "StateWrapper", ("cell_states", "log_probs", "finished", "lengths"))

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- beam tensor helpers (reference tile_beam_merge_with_batch) --------
    def _merge(self, x):
        """[B, K, ...] -> [B*K, ...]"""
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        return Tensor(v.reshape((-1,) + v.shape[2:]))

    def _split(self, x):
        """[B*K, ...] -> [B, K, ...]"""
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        return Tensor(v.reshape((-1, self.beam_size) + v.shape[1:]))

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """Repeat a batch tensor for each beam: [B, ...] -> [B*K, ...]."""
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        tiled = jnp.repeat(v[:, None], beam_size, axis=1)
        return Tensor(tiled.reshape((-1,) + v.shape[1:]))

    def initialize(self, initial_cell_states):
        states = jnp.asarray(
            initial_cell_states._value
            if isinstance(initial_cell_states, Tensor)
            else initial_cell_states)
        batch = states.shape[0]
        k = self.beam_size
        cell_states = self.tile_beam_merge_with_batch(
            Tensor(states), k)
        # beam 0 live, others dead (-inf) so step 1 expands a single beam
        log_probs = jnp.tile(
            jnp.array([0.0] + [-1e9] * (k - 1), jnp.float32), (batch, 1))
        finished = jnp.zeros((batch, k), bool)
        lengths = jnp.zeros((batch, k), jnp.int64)
        ids = Tensor(jnp.full((batch * k,), self.start_token, jnp.int64))
        inputs = self.embedding_fn(ids) if self.embedding_fn else ids
        return inputs, self.StateWrapper(cell_states, log_probs, finished,
                                         lengths), Tensor(finished)

    def step(self, time, inputs, states, **kwargs):
        cell_out, next_cell_states = self.cell(inputs, states.cell_states,
                                               **kwargs)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        logits = cell_out._value if isinstance(cell_out, Tensor) \
            else jnp.asarray(cell_out)
        k = self.beam_size
        vocab = logits.shape[-1]
        batch = logits.shape[0] // k
        step_lp = jax.nn.log_softmax(logits, axis=-1).reshape(
            (batch, k, vocab))
        # finished beams only extend with end_token at zero cost
        frozen = jnp.full((vocab,), -1e9).at[self.end_token].set(0.0)
        step_lp = jnp.where(states.finished[..., None], frozen, step_lp)
        total = states.log_probs[..., None] + step_lp
        flat = total.reshape(batch, k * vocab)
        top_lp, top_idx = jax.lax.top_k(flat, k)
        parent = (top_idx // vocab).astype(jnp.int64)
        token = (top_idx % vocab).astype(jnp.int64)

        bi = jnp.arange(batch)[:, None]
        finished = states.finished[bi, parent] | (token == self.end_token)
        lengths = states.lengths[bi, parent] + (~finished).astype(jnp.int64)

        # reorder cell states by parent beam
        cells = next_cell_states._value if isinstance(next_cell_states,
                                                      Tensor) \
            else jnp.asarray(next_cell_states)
        cells = cells.reshape((batch, k) + cells.shape[1:])
        cells = cells[bi, parent].reshape((batch * k,) + cells.shape[2:])

        out = self.OutputWrapper(Tensor(top_lp), Tensor(token),
                                 Tensor(parent))
        nstate = self.StateWrapper(Tensor(cells), top_lp, finished, lengths)
        ids = Tensor(token.reshape(-1))
        nxt = self.embedding_fn(ids) if self.embedding_fn else ids
        return out, nstate, nxt, Tensor(finished)

    def finalize(self, outputs, final_states, sequence_lengths):
        """Backtrace parent pointers into contiguous sequences via the
        gather_tree op: ids/parents stacked [T, B, K]."""
        ids = api.stack([o.predicted_ids for o in outputs], 0)
        parents = api.stack([o.parent_ids for o in outputs], 0)
        final = api.gather_tree(ids, parents)
        return final, final_states

    @property
    def tracks_own_finished(self):
        return True


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Run decoder.initialize, step until every sequence is finished or
    max_step_num, then finalize (reference nn/decode.py dynamic_decode)."""
    inputs, states, finished = decoder.initialize(inits)
    outputs = []
    step = 0
    fin = np.asarray(finished._value if isinstance(finished, Tensor)
                     else finished)
    while not fin.all():
        if max_step_num is not None and step >= max_step_num:
            break
        out, states, inputs, finished = decoder.step(step, inputs, states,
                                                     **kwargs)
        outputs.append(out)
        fin = np.asarray(finished._value if isinstance(finished, Tensor)
                         else finished)
        step += 1
    lengths = getattr(states, "lengths", None)
    final_outputs, final_states = decoder.finalize(outputs, states, lengths)
    if not output_time_major and isinstance(final_outputs, Tensor):
        # reference _transpose_batch_time: swap ONLY time<->batch, giving
        # [batch, time, beam]
        if final_outputs.ndim >= 2:
            perm = [1, 0] + list(range(2, final_outputs.ndim))
            final_outputs = api.transpose(final_outputs, perm)
    if return_length:
        return final_outputs, final_states, Tensor(jnp.asarray(
            lengths if lengths is not None else 0))
    return final_outputs, final_states


import jax  # noqa: E402  (top_k in step)
