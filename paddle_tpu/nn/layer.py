"""nn.Layer: module base class.

Reference: python/paddle/nn/layer/layers.py (Layer) + EagerParamBase
(python/paddle/fluid/framework.py:6967). Parameters are Tensors with
stop_gradient=False; buffers are non-trainable state (e.g. BN running stats).
"""
from __future__ import annotations

import collections
from typing import Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype, get_default_dtype
from ..core.tensor import Tensor
from ..core.autograd import no_grad


class Parameter(Tensor):
    """Trainable parameter (EagerParamBase analog)."""

    def __init__(self, value, name=None, trainable=True):
        if isinstance(value, Tensor):
            value = value._value
        super().__init__(value, stop_gradient=not trainable, name=name or _unique_name("param"))
        self.persistable = True
        self.trainable = trainable

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


jax.tree_util.register_pytree_node(
    Parameter,
    lambda t: ((t._value,), (t.stop_gradient, t.name)),
    lambda aux, vals: _unflatten_param(aux, vals),
)


def _unflatten_param(aux, vals):
    t = Parameter.__new__(Parameter)
    t._value = vals[0]
    t.stop_gradient = aux[0]
    t._grad_node = None
    t._grad = None
    t._grad_hooks = []
    t.name = aux[1]
    t.persistable = True
    t.trainable = not aux[0]
    return t


_layer_counter = collections.defaultdict(int)


def _unique_name(prefix):
    _layer_counter[prefix] += 1
    return f"{prefix}_{_layer_counter[prefix] - 1}"


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype=None):
        object.__setattr__(self, "_parameters", collections.OrderedDict())
        object.__setattr__(self, "_buffers", collections.OrderedDict())
        object.__setattr__(self, "_sub_layers", collections.OrderedDict())
        self.training = True
        self._dtype = convert_dtype(dtype) if dtype else get_default_dtype()
        self._full_name = _unique_name(name_scope or self.__class__.__name__.lower())
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()

    # --- registration ------------------------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Parameter):
            self._parameters[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            self._sub_layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if name in getattr(self, "_parameters", {}):
                if value is None or isinstance(value, Tensor):
                    self._parameters[name] = value
                    return
            if name in getattr(self, "_buffers", {}):
                self._buffers[name] = value
                return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in (self._parameters, self._buffers, self._sub_layers):
            if name in store:
                del store[name]
                return
        object.__delattr__(self, name)

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor)
        if tensor is not None:
            tensor.persistable = persistable
            tensor.stop_gradient = True
        self._buffers[name] = tensor
        return tensor

    def create_parameter(
        self, shape, attr=None, dtype=None, is_bias=False, default_initializer=None,
    ) -> Parameter:
        from . import initializer as I

        dtype = convert_dtype(dtype) if dtype else self._dtype
        init = default_initializer
        if init is None and attr is not None and getattr(attr, "initializer", None) is not None:
            init = attr.initializer
        if init is None:
            init = I._global_bias_init if is_bias else I._global_weight_init
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierUniform()
        value = init(shape, dtype)
        name = None
        if attr is not None and getattr(attr, "name", None):
            name = attr.name
        p = Parameter(value, name=name)
        if attr is not None and getattr(attr, "trainable", True) is False:
            p.stop_gradient = True
            p.trainable = False
        return p

    # --- traversal ---------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer_prefix, layer in self._walk(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is not None and id(p) not in seen:
                    seen.add(id(p))
                    yield (f"{layer_prefix}.{pname}" if layer_prefix else pname), p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer_prefix, layer in self._walk(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is not None and id(b) not in seen:
                    seen.add(id(b))
                    yield (f"{layer_prefix}.{bname}" if layer_prefix else bname), b

    def _walk(self, prefix="", include_sublayers=True):
        yield self._full_name, prefix, self
        if include_sublayers:
            for name, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = f"{prefix}.{name}" if prefix else name
                yield from sub._walk(sub_prefix, True)

    def children(self):
        return iter(self._sub_layers.values())

    def named_children(self):
        return iter(self._sub_layers.items())

    def sublayers(self, include_self=False):
        out = [self] if include_self else []
        for sub in self._sub_layers.values():
            if sub is not None:
                out.extend(sub.sublayers(include_self=True))
        return out

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            p = f"{prefix}.{name}" if prefix else name
            yield from sub.named_sublayers(p, include_self=True)

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    # --- mode --------------------------------------------------------------
    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    # --- state dict --------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True, structured_name_prefix=""):
        out = destination if destination is not None else collections.OrderedDict()
        for k, p in self.named_parameters(structured_name_prefix, include_sublayers):
            out[k] = p
        for k, b in self.named_buffers(structured_name_prefix, include_sublayers):
            out[k] = b
        return out

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k in own:
                val = v._value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
                own[k]._value = val.astype(own[k].dtype)
            else:
                unexpected.append(k)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # --- dtype / device ----------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = convert_dtype(dtype)
            with no_grad():
                for p in self.parameters():
                    if jnp.issubdtype(p.dtype, jnp.floating):
                        p._value = p._value.astype(dt)
                for b in self.buffers():
                    if jnp.issubdtype(b.dtype, jnp.floating):
                        b._value = b._value.astype(dt)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # --- hooks -------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        handle = _RemovableHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = _RemovableHandle(self._forward_post_hooks)
        self._forward_post_hooks[handle.id] = hook
        return handle

    # --- call --------------------------------------------------------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, args)
            if result is not None:
                args = result if isinstance(result, tuple) else (result,)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, args, out)
            if result is not None:
                out = result
        return out

    def full_name(self):
        return self._full_name

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = [sub_repr[0]] + ["  " + l for l in sub_repr[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub_repr))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"


class _RemovableHandle:
    _next_id = 0

    def __init__(self, container):
        self._container = container
        self.id = _RemovableHandle._next_id
        _RemovableHandle._next_id += 1

    def remove(self):
        self._container.pop(self.id, None)


class ParamAttr:
    """paddle.ParamAttr — parameter configuration bundle."""

    def __init__(
        self, name=None, initializer=None, learning_rate=1.0, regularizer=None,
        trainable=True, need_clip=True,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip
