"""paddle.nn.functional — thin paddle-signature layer over the op registry.

Reference: python/paddle/nn/functional/*. Most functions ARE the registered
ops; only signature shims live here.
"""
from __future__ import annotations

from ..ops.api import (  # noqa: F401
    adaptive_avg_pool2d,
    adaptive_max_pool2d,
    avg_pool2d,
    batch_norm,
    binary_cross_entropy,
    binary_cross_entropy_with_logits,
    celu,
    conv1d,
    conv2d,
    conv2d_transpose,
    cosine_similarity,
    cross_entropy,
    dropout,
    dropout2d,
    elu,
    embedding as _embedding_op,
    gelu,
    glu,
    group_norm,
    gumbel_softmax,
    hardshrink,
    hardsigmoid,
    hardswish,
    hardtanh,
    hinge_embedding_loss,
    instance_norm,
    interpolate,
    kl_div,
    l1_loss,
    label_smooth,
    layer_norm as _layer_norm_op,
    leaky_relu,
    linear,
    log_sigmoid,
    log_softmax,
    max_pool2d,
    maxout,
    mish,
    mse_loss,
    nll_loss,
    normalize,
    one_hot,
    pad,
    pixel_shuffle,
    prelu,
    relu,
    relu6,
    rms_norm,
    rrelu,
    selu,
    sigmoid,
    sigmoid_focal_loss,
    silu,
    smooth_l1_loss,
    softmax,
    softplus,
    softshrink,
    swish,
    tanhshrink,
    thresholded_relu,
    unfold,
    scaled_dot_product_attention,
    conv3d,
    conv1d_transpose,
    conv3d_transpose,
    max_pool1d,
    avg_pool1d,
    max_pool3d,
    avg_pool3d,
    max_unpool1d,
    max_unpool2d,
    adaptive_avg_pool1d,
    adaptive_max_pool1d,
    adaptive_avg_pool3d,
    adaptive_max_pool3d,
    lp_pool2d,
    grid_sample,
    affine_grid,
    pixel_unshuffle,
    channel_shuffle,
    fold,
    local_response_norm,
    softsign,
    alpha_dropout,
    dropout3d,
    zeropad2d,
    ctc_loss,
    margin_ranking_loss,
    pairwise_distance,
    triplet_margin_loss,
    triplet_margin_with_distance_loss,
    cosine_embedding_loss,
    soft_margin_loss,
    multi_label_soft_margin_loss,
    multi_margin_loss,
    poisson_nll_loss,
    gaussian_nll_loss,
    square_error_cost,
    log_loss,
    dice_loss,
    npair_loss,
    hsigmoid_loss,
)
from ..ops.api import softmax as softmax_  # noqa: F401
from ..ops import api as _api


def embedding(x, weight, padding_idx=None, sparse=False):
    return _embedding_op(x, weight, padding_idx=padding_idx)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5):
    return _layer_norm_op(x, normalized_shape, weight, bias, epsilon)


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False, data_format="NCHW"):
    return interpolate(x, size, scale_factor, mode, align_corners, data_format)


def tanh(x):
    return _api.tanh(x)


def flatten(x, start_axis=0, stop_axis=-1):
    return _api.flatten(x, start_axis, stop_axis)


def square_error_cost(input, label):
    return _api.square(input - label)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, axis=-1, return_softmax=False):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index, axis=axis, reduction="none")
    if loss.ndim == logits.ndim - 1:
        loss = _api.unsqueeze(loss, axis)
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    import jax.numpy as jnp

    if maxlen is None:
        maxlen = int(lengths.max().item())
    rng = _api.arange(0, maxlen, 1, dtype="int64")
    return _api.cast(_api.less_than(rng, _api.unsqueeze(lengths, -1)), dtype)
