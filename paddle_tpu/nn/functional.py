"""paddle.nn.functional — thin paddle-signature layer over the op registry.

Reference: python/paddle/nn/functional/*. Most functions ARE the registered
ops; only signature shims live here.
"""
from __future__ import annotations

from ..ops.api import (  # noqa: F401
    adaptive_avg_pool2d,
    adaptive_max_pool2d,
    avg_pool2d,
    batch_norm,
    binary_cross_entropy,
    binary_cross_entropy_with_logits,
    celu,
    conv1d,
    conv2d,
    conv2d_transpose,
    cosine_similarity,
    cross_entropy,
    dropout,
    dropout2d,
    elu,
    embedding as _embedding_op,
    gelu,
    glu,
    group_norm,
    gumbel_softmax,
    hardshrink,
    hardsigmoid,
    hardswish,
    hardtanh,
    hinge_embedding_loss,
    instance_norm,
    interpolate,
    kl_div,
    l1_loss,
    label_smooth,
    layer_norm as _layer_norm_op,
    leaky_relu,
    linear,
    log_sigmoid,
    log_softmax,
    max_pool2d,
    maxout,
    mish,
    mse_loss,
    nll_loss,
    normalize,
    one_hot,
    pad,
    pixel_shuffle,
    prelu,
    relu,
    relu6,
    rms_norm,
    rrelu,
    selu,
    sigmoid,
    sigmoid_focal_loss,
    silu,
    smooth_l1_loss,
    softmax,
    softplus,
    softshrink,
    swish,
    tanhshrink,
    thresholded_relu,
    unfold,
    scaled_dot_product_attention,
    conv3d,
    conv1d_transpose,
    conv3d_transpose,
    max_pool1d,
    avg_pool1d,
    max_pool3d,
    avg_pool3d,
    max_unpool1d,
    max_unpool2d,
    adaptive_avg_pool1d,
    adaptive_max_pool1d,
    adaptive_avg_pool3d,
    adaptive_max_pool3d,
    lp_pool2d,
    grid_sample,
    affine_grid,
    pixel_unshuffle,
    channel_shuffle,
    fold,
    local_response_norm,
    softsign,
    alpha_dropout,
    dropout3d,
    zeropad2d,
    ctc_loss,
    margin_ranking_loss,
    pairwise_distance,
    triplet_margin_loss,
    triplet_margin_with_distance_loss,
    cosine_embedding_loss,
    soft_margin_loss,
    multi_label_soft_margin_loss,
    multi_margin_loss,
    poisson_nll_loss,
    gaussian_nll_loss,
    square_error_cost,
    log_loss,
    dice_loss,
    npair_loss,
    hsigmoid_loss,
)
def softmax_(x, axis=-1, dtype=None, name=None):
    """In-place softmax (reference F.softmax_): rebinds x's value like the
    other *_ shims — the previous alias to the out-of-place op silently
    left x untouched."""
    out = _api.softmax(x, axis=axis)
    x._value = out._value
    x._grad_node = out._grad_node
    if not out.stop_gradient:
        x.stop_gradient = False
    return x
from ..ops import api as _api


def embedding(x, weight, padding_idx=None, sparse=False):
    return _embedding_op(x, weight, padding_idx=padding_idx)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5):
    return _layer_norm_op(x, normalized_shape, weight, bias, epsilon)


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False, data_format="NCHW"):
    return interpolate(x, size, scale_factor, mode, align_corners, data_format)


def tanh(x):
    return _api.tanh(x)


def flatten(x, start_axis=0, stop_axis=-1):
    return _api.flatten(x, start_axis, stop_axis)


def square_error_cost(input, label):
    return _api.square(input - label)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, axis=-1, return_softmax=False):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index, axis=axis, reduction="none")
    if loss.ndim == logits.ndim - 1:
        loss = _api.unsqueeze(loss, axis)
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    if maxlen is None:
        maxlen = int(lengths.max().item())
    rng = _api.arange(0, maxlen, 1, dtype="int64")
    return _api.cast(_api.less_than(rng, _api.unsqueeze(lengths, -1)), dtype)


# -- round-5 API parity (reference nn/functional/__init__.py __all__) -------

from ..ops.api import (  # noqa: F401, E402
    bilinear,
    class_center_sample,
    diag_embed,
    gather_tree,
    max_unpool3d,
    temporal_shift,
)
from ..ops.api import margin_cross_entropy as _margin_ce_op  # noqa: E402
from ..ops.api import rnnt_loss as _rnnt_op  # noqa: E402


def _reduce(loss, reduction):
    if reduction == "mean":
        return _api.mean(loss)
    if reduction == "sum":
        return _api.sum(loss)
    return loss


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    return _reduce(_rnnt_op(input, label, input_lengths, label_lengths,
                            blank, fastemit_lambda), reduction)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    out = _margin_ce_op(logits, label, margin1, margin2, margin3, scale,
                        return_softmax=return_softmax)
    if return_softmax:
        loss, sm = out
        return _reduce(loss, reduction), sm
    return _reduce(out, reduction)


def relu_(x):
    return x.relu_()


def elu_(x, alpha=1.0):
    out = _api.elu(x, alpha)
    x._value = out._value
    x._grad_node = out._grad_node
    if not out.stop_gradient:
        x.stop_gradient = False
    return x


def tanh_(x):
    return x.tanh_()


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None):
    """Block-sparse attention with a per-head CSR connectivity pattern
    (reference phi/kernels/sparse/gpu/sparse_attention via cusparse; here
    the CSR pattern gates a masked dense softmax — exact semantics, with
    the density caveat documented: for long-sequence sparse patterns use
    paddle_tpu.sparse attention or flash_attn_unpadded, which tile).

    query/key/value: [B, H, T, D]; offset: [B, H, T+1]; columns: [B, H, nnz].
    """
    import jax.numpy as jnp

    from ..core.tensor import Tensor as _T

    off = sparse_csr_offset._value if hasattr(sparse_csr_offset, "_value") \
        else jnp.asarray(sparse_csr_offset)
    cols = sparse_csr_columns._value if hasattr(sparse_csr_columns, "_value") \
        else jnp.asarray(sparse_csr_columns)
    b, h, t, d = query.shape
    nnz = cols.shape[-1]
    # CSR pattern -> boolean mask (integer-only; grads flow through q/k/v
    # below via registered ops). Row of each slot: searchsorted on offsets.
    slot = jnp.arange(nnz)
    rows = jax.vmap(jax.vmap(
        lambda o: jnp.searchsorted(o, slot, side="right") - 1))(off)
    mask = jnp.zeros((b, h, t, t), bool)
    bi = jnp.arange(b)[:, None, None]
    hi = jnp.arange(h)[None, :, None]
    valid = slot[None, None, :] < off[..., -1:]
    mask = mask.at[bi, hi, jnp.clip(rows, 0, t - 1),
                   jnp.clip(cols, 0, t - 1)].max(valid)
    neg = _T(jnp.where(mask, 0.0, -1e30).astype(jnp.float32))
    scores = _api.scale(
        _api.matmul(query, key, transpose_y=True), 1.0 / (d ** 0.5))
    scores = _api.add(scores, neg)
    if attn_mask is not None:
        scores = _api.add(scores, attn_mask)
    p = softmax(scores, axis=-1)
    return _api.matmul(p, value)


import jax  # noqa: E402  (used by sparse_attention row recovery)
