// Host tracer: low-overhead span recording with chrome-trace export.
//
// Capability parity target: the reference's host-side profiler —
// RecordEvent ranges collected into per-thread ring buffers
// (paddle/fluid/platform/profiler/host_tracer.h:26,
//  host_event_recorder.h) and exported as chrome-trace JSON
// (chrometracing_logger.cc). Device timelines on TPU come from XLA/xprof,
// so the native work is exactly this host-span layer.
//
// Design: per-thread span buffers (no lock on the hot path except a
// one-time registration), steady_clock nanosecond timestamps, nested
// spans via a thread-local open-span stack.

#include <sys/syscall.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

inline uint64_t now_ns() {
  return static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::nanoseconds>(
          std::chrono::steady_clock::now().time_since_epoch())
          .count());
}

struct Span {
  std::string name;
  uint64_t begin_ns;
  uint64_t end_ns;
  int64_t tid;
};

struct Counter {
  std::string name;
  uint64_t ts_ns;
  double value;
};

struct ThreadBuffer {
  std::mutex mu;  // guards spans/open: owner thread appends, readers dump
  std::vector<Span> spans;
  std::vector<std::pair<std::string, uint64_t>> open;  // name, begin
  int64_t tid;
};

std::mutex g_mu;
std::vector<ThreadBuffer*> g_buffers;
std::vector<Counter> g_counters;
std::atomic<bool> g_enabled{false};

ThreadBuffer* tls_buffer() {
  thread_local ThreadBuffer* buf = [] {
    auto* b = new ThreadBuffer();
    b->tid = static_cast<int64_t>(::syscall(SYS_gettid));
    std::lock_guard<std::mutex> lk(g_mu);
    g_buffers.push_back(b);
    return b;
  }();
  return buf;
}

void json_escape(const std::string& in, std::string* out) {
  for (char c : in) {
    switch (c) {
      case '"': *out += "\\\""; break;
      case '\\': *out += "\\\\"; break;
      case '\n': *out += "\\n"; break;
      case '\t': *out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) >= 0x20) out->push_back(c);
    }
  }
}

}  // namespace

extern "C" {

void pt_trace_enable(int on) { g_enabled.store(on != 0); }

int pt_trace_enabled() { return g_enabled.load() ? 1 : 0; }

void pt_trace_push(const char* name) {
  if (!g_enabled.load(std::memory_order_relaxed)) return;
  ThreadBuffer* b = tls_buffer();
  std::lock_guard<std::mutex> lk(b->mu);
  b->open.emplace_back(name, now_ns());
}

void pt_trace_pop() {
  if (!g_enabled.load(std::memory_order_relaxed)) return;
  ThreadBuffer* b = tls_buffer();
  std::lock_guard<std::mutex> lk(b->mu);
  if (b->open.empty()) return;
  auto [name, begin] = std::move(b->open.back());
  b->open.pop_back();
  b->spans.push_back({std::move(name), begin, now_ns(), b->tid});
}

// Record a fully-formed span (for Python-side timestamps).
void pt_trace_span(const char* name, uint64_t begin_ns, uint64_t end_ns) {
  if (!g_enabled.load(std::memory_order_relaxed)) return;
  ThreadBuffer* b = tls_buffer();
  std::lock_guard<std::mutex> lk(b->mu);
  b->spans.push_back({name, begin_ns, end_ns, b->tid});
}

void pt_trace_counter(const char* name, double value) {
  if (!g_enabled.load(std::memory_order_relaxed)) return;
  std::lock_guard<std::mutex> lk(g_mu);
  g_counters.push_back({name, now_ns(), value});
}

uint64_t pt_trace_now_ns() { return now_ns(); }

void pt_trace_clear() {
  std::lock_guard<std::mutex> lk(g_mu);
  for (auto* b : g_buffers) {
    std::lock_guard<std::mutex> blk(b->mu);
    b->spans.clear();
    b->open.clear();
  }
  g_counters.clear();
}

long pt_trace_num_spans() {
  std::lock_guard<std::mutex> lk(g_mu);
  long n = 0;
  for (auto* b : g_buffers) {
    std::lock_guard<std::mutex> blk(b->mu);
    n += static_cast<long>(b->spans.size());
  }
  return n;
}

// Writes a chrome://tracing JSON file. Returns 0 on success.
int pt_trace_dump(const char* path) {
  std::lock_guard<std::mutex> lk(g_mu);
  FILE* f = std::fopen(path, "w");
  if (!f) return -1;
  std::fputs("{\"traceEvents\":[\n", f);
  bool first = true;
  int pid = static_cast<int>(::getpid());
  for (auto* b : g_buffers) {
    std::lock_guard<std::mutex> blk(b->mu);
    for (const Span& s : b->spans) {
      std::string esc;
      json_escape(s.name, &esc);
      std::fprintf(
          f, "%s{\"name\":\"%s\",\"ph\":\"X\",\"pid\":%d,\"tid\":%lld,"
             "\"ts\":%.3f,\"dur\":%.3f}",
          first ? "" : ",\n", esc.c_str(), pid,
          static_cast<long long>(s.tid), s.begin_ns / 1e3,
          (s.end_ns - s.begin_ns) / 1e3);
      first = false;
    }
  }
  for (const Counter& c : g_counters) {
    std::string esc;
    json_escape(c.name, &esc);
    std::fprintf(f,
                 "%s{\"name\":\"%s\",\"ph\":\"C\",\"pid\":%d,\"ts\":%.3f,"
                 "\"args\":{\"value\":%g}}",
                 first ? "" : ",\n", esc.c_str(), pid, c.ts_ns / 1e3,
                 c.value);
    first = false;
  }
  std::fputs("\n]}\n", f);
  std::fclose(f);
  return 0;
}

// Copy span i (global index across threads) into out fields. Returns 0
// on success, -1 if out of range. name is truncated to cap.
int pt_trace_get_span(long i, char* name, int cap, uint64_t* begin_ns,
                      uint64_t* end_ns, int64_t* tid) {
  std::lock_guard<std::mutex> lk(g_mu);
  long k = 0;
  for (auto* b : g_buffers) {
    std::lock_guard<std::mutex> blk(b->mu);
    if (i < k + static_cast<long>(b->spans.size())) {
      const Span& s = b->spans[static_cast<size_t>(i - k)];
      std::snprintf(name, static_cast<size_t>(cap), "%s", s.name.c_str());
      *begin_ns = s.begin_ns;
      *end_ns = s.end_ns;
      *tid = s.tid;
      return 0;
    }
    k += static_cast<long>(b->spans.size());
  }
  return -1;
}

}  // extern "C"
