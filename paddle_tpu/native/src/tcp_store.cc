// TCPStore: a minimal TCP key-value rendezvous store for multi-host
// bootstrap (set/get/add/wait/barrier), C++ with a C API for ctypes.
//
// Capability parity target: the reference framework's TCPStore
// (paddle/phi/core/distributed/store/tcp_store.h:120, tcp_utils.cc) —
// master rank hosts the store, workers connect over TCP, collective
// bootstrap does set/get of unique ids and add-based barriers.
// This is a fresh TPU-framework implementation (single-threaded
// poll()-based server with parked blocking reads), not a translation.
//
// Wire protocol (little-endian):
//   request : [u8 cmd][u32 klen][key bytes][u32 vlen][value bytes]
//   response: [u8 status][u32 vlen][value bytes]
// cmds: SET=1 GET=2(block until key exists) ADD=3(value=i64 delta,
//       returns new counter) WAITGE=4(value=i64 target; blocks until
//       counter>=target) DEL=5 NUMKEYS=6 GETNB=7(non-blocking get)

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

enum Cmd : uint8_t {
  kSet = 1,
  kGet = 2,
  kAdd = 3,
  kWaitGe = 4,
  kDel = 5,
  kNumKeys = 6,
  kGetNb = 7,
};

enum Status : uint8_t { kOk = 0, kMissing = 1, kError = 2 };

struct PendingWait {
  int fd;
  uint8_t cmd;  // kGet or kWaitGe
  std::string key;
  int64_t target;  // for kWaitGe
};

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_resp(int fd, uint8_t status, const void* val, uint32_t vlen) {
  std::vector<char> out(1 + 4 + vlen);
  out[0] = static_cast<char>(status);
  std::memcpy(out.data() + 1, &vlen, 4);
  if (vlen) std::memcpy(out.data() + 5, val, vlen);
  return send_all(fd, out.data(), out.size());
}

class StoreServer {
 public:
  explicit StoreServer(int port) : port_(port) {}

  // Returns the bound port (useful when port==0), or -1 on failure.
  int Start() {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return -1;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
        0) {
      ::close(listen_fd_);
      return -1;
    }
    if (::listen(listen_fd_, 128) < 0) {
      ::close(listen_fd_);
      return -1;
    }
    socklen_t len = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    port_ = ntohs(addr.sin_port);
    running_.store(true);
    thread_ = std::thread([this] { Loop(); });
    return port_;
  }

  void Stop() {
    running_.store(false);
    if (listen_fd_ >= 0) ::shutdown(listen_fd_, SHUT_RDWR);
    if (thread_.joinable()) thread_.join();
    if (listen_fd_ >= 0) ::close(listen_fd_);
    for (int fd : clients_) ::close(fd);
    clients_.clear();
  }

  ~StoreServer() { Stop(); }

 private:
  void Loop() {
    while (running_.load()) {
      std::vector<pollfd> fds;
      fds.push_back({listen_fd_, POLLIN, 0});
      for (int fd : clients_) fds.push_back({fd, POLLIN, 0});
      int rc = ::poll(fds.data(), fds.size(), 200 /*ms*/);
      if (rc < 0) {
        if (errno == EINTR) continue;
        break;
      }
      if (fds[0].revents & POLLIN) {
        int cfd = ::accept(listen_fd_, nullptr, nullptr);
        if (cfd >= 0) {
          int one = 1;
          ::setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          clients_.push_back(cfd);
        }
      }
      // Iterate over a copy; HandleRequest may close/remove fds.
      std::vector<int> ready;
      for (size_t i = 1; i < fds.size(); ++i) {
        if (fds[i].revents & (POLLIN | POLLHUP | POLLERR)) {
          ready.push_back(fds[i].fd);
        }
      }
      for (int fd : ready) {
        if (!HandleRequest(fd)) DropClient(fd);
      }
    }
  }

  void DropClient(int fd) {
    ::close(fd);
    for (size_t i = 0; i < clients_.size(); ++i) {
      if (clients_[i] == fd) {
        clients_.erase(clients_.begin() + i);
        break;
      }
    }
    for (size_t i = 0; i < pending_.size();) {
      if (pending_[i].fd == fd) {
        pending_.erase(pending_.begin() + i);
      } else {
        ++i;
      }
    }
  }

  bool HandleRequest(int fd) {
    uint8_t cmd;
    uint32_t klen, vlen;
    if (!recv_all(fd, &cmd, 1) || !recv_all(fd, &klen, 4)) return false;
    if (klen > (1u << 20)) return false;
    std::string key(klen, '\0');
    if (klen && !recv_all(fd, key.data(), klen)) return false;
    if (!recv_all(fd, &vlen, 4)) return false;
    if (vlen > (64u << 20)) return false;
    std::string val(vlen, '\0');
    if (vlen && !recv_all(fd, val.data(), vlen)) return false;

    switch (cmd) {
      case kSet: {
        kv_[key] = val;
        WakeWaiters(key);
        return send_resp(fd, kOk, nullptr, 0);
      }
      case kGetNb: {
        auto it = kv_.find(key);
        if (it == kv_.end()) return send_resp(fd, kMissing, nullptr, 0);
        return send_resp(fd, kOk, it->second.data(),
                         static_cast<uint32_t>(it->second.size()));
      }
      case kGet: {
        auto it = kv_.find(key);
        if (it == kv_.end()) {
          pending_.push_back({fd, kGet, key, 0});
          return true;  // parked; reply comes on SET
        }
        return send_resp(fd, kOk, it->second.data(),
                         static_cast<uint32_t>(it->second.size()));
      }
      case kAdd: {
        int64_t delta = 0;
        if (val.size() == 8) std::memcpy(&delta, val.data(), 8);
        int64_t cur = 0;
        auto it = kv_.find(key);
        if (it != kv_.end() && it->second.size() == 8) {
          std::memcpy(&cur, it->second.data(), 8);
        }
        cur += delta;
        std::string packed(8, '\0');
        std::memcpy(packed.data(), &cur, 8);
        kv_[key] = packed;
        WakeWaiters(key);
        return send_resp(fd, kOk, &cur, 8);
      }
      case kWaitGe: {
        int64_t target = 0;
        if (val.size() == 8) std::memcpy(&target, val.data(), 8);
        int64_t cur = Counter(key);
        if (cur >= target) return send_resp(fd, kOk, &cur, 8);
        pending_.push_back({fd, kWaitGe, key, target});
        return true;  // parked
      }
      case kDel: {
        kv_.erase(key);
        return send_resp(fd, kOk, nullptr, 0);
      }
      case kNumKeys: {
        int64_t n = static_cast<int64_t>(kv_.size());
        return send_resp(fd, kOk, &n, 8);
      }
      default:
        return send_resp(fd, kError, nullptr, 0);
    }
  }

  int64_t Counter(const std::string& key) {
    auto it = kv_.find(key);
    int64_t cur = 0;
    if (it != kv_.end() && it->second.size() == 8) {
      std::memcpy(&cur, it->second.data(), 8);
    }
    return cur;
  }

  void WakeWaiters(const std::string& key) {
    for (size_t i = 0; i < pending_.size();) {
      PendingWait& w = pending_[i];
      bool done = false;
      if (w.key == key) {
        if (w.cmd == kGet) {
          const std::string& v = kv_[key];
          send_resp(w.fd, kOk, v.data(), static_cast<uint32_t>(v.size()));
          done = true;
        } else if (w.cmd == kWaitGe) {
          int64_t cur = Counter(key);
          if (cur >= w.target) {
            send_resp(w.fd, kOk, &cur, 8);
            done = true;
          }
        }
      }
      if (done) {
        pending_.erase(pending_.begin() + i);
      } else {
        ++i;
      }
    }
  }

  int port_;
  int listen_fd_ = -1;
  std::atomic<bool> running_{false};
  std::thread thread_;
  std::vector<int> clients_;
  std::vector<PendingWait> pending_;
  std::map<std::string, std::string> kv_;
};

struct StoreClient {
  int fd = -1;
  std::mutex mu;  // one outstanding request at a time per client
};

int connect_with_timeout(const char* host, int port, int timeout_ms) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return -1;
  }
  // Retry loop: the server may not be up yet (rendezvous races).
  int waited = 0;
  while (true) {
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      return fd;
    }
    ::close(fd);
    if (waited >= timeout_ms) return -1;
    ::usleep(50 * 1000);
    waited += 50;
    fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
  }
}

// status<0 on transport error; else resp status. *out resized to payload.
int client_rpc(StoreClient* c, uint8_t cmd, const std::string& key,
               const void* val, uint32_t vlen, std::string* out) {
  std::lock_guard<std::mutex> lk(c->mu);
  uint32_t klen = static_cast<uint32_t>(key.size());
  std::vector<char> req(1 + 4 + klen + 4 + vlen);
  req[0] = static_cast<char>(cmd);
  std::memcpy(req.data() + 1, &klen, 4);
  std::memcpy(req.data() + 5, key.data(), klen);
  std::memcpy(req.data() + 5 + klen, &vlen, 4);
  if (vlen) std::memcpy(req.data() + 9 + klen, val, vlen);
  if (!send_all(c->fd, req.data(), req.size())) return -1;
  uint8_t status;
  uint32_t rlen;
  if (!recv_all(c->fd, &status, 1) || !recv_all(c->fd, &rlen, 4)) return -1;
  out->resize(rlen);
  if (rlen && !recv_all(c->fd, out->data(), rlen)) return -1;
  return status;
}

}  // namespace

extern "C" {

void* pt_store_server_start(int port, int* bound_port) {
  auto* s = new StoreServer(port);
  int p = s->Start();
  if (p < 0) {
    delete s;
    return nullptr;
  }
  if (bound_port) *bound_port = p;
  return s;
}

void pt_store_server_stop(void* h) { delete static_cast<StoreServer*>(h); }

void* pt_store_client_connect(const char* host, int port, int timeout_ms) {
  int fd = connect_with_timeout(host, port, timeout_ms);
  if (fd < 0) return nullptr;
  auto* c = new StoreClient();
  c->fd = fd;
  return c;
}

void pt_store_client_close(void* h) {
  auto* c = static_cast<StoreClient*>(h);
  if (c->fd >= 0) ::close(c->fd);
  delete c;
}

int pt_store_set(void* h, const char* key, const void* val, uint32_t vlen) {
  std::string out;
  return client_rpc(static_cast<StoreClient*>(h), kSet, key, val, vlen, &out);
}

// Blocking get. Returns the full payload length (which may exceed cap),
// or -1 on transport error, -2 if missing (non-blocking mode). Copies
// min(len, cap) bytes; callers re-issue with a larger buffer when the
// return value exceeds cap.
long pt_store_get(void* h, const char* key, void* buf, uint32_t cap,
                  int blocking) {
  std::string out;
  int st = client_rpc(static_cast<StoreClient*>(h),
                      blocking ? kGet : kGetNb, key, nullptr, 0, &out);
  if (st < 0 || st == kError) return -1;
  if (st == kMissing) return -2;
  uint32_t n = static_cast<uint32_t>(out.size());
  std::memcpy(buf, out.data(), n < cap ? n : cap);
  return static_cast<long>(n);
}

long pt_store_add(void* h, const char* key, long delta) {
  int64_t d = delta;
  std::string out;
  int st =
      client_rpc(static_cast<StoreClient*>(h), kAdd, key, &d, 8, &out);
  if (st != kOk || out.size() != 8) return -1;
  int64_t v;
  std::memcpy(&v, out.data(), 8);
  return static_cast<long>(v);
}

// Blocks until counter(key) >= target. Returns counter value or -1.
long pt_store_wait_ge(void* h, const char* key, long target) {
  int64_t t = target;
  std::string out;
  int st =
      client_rpc(static_cast<StoreClient*>(h), kWaitGe, key, &t, 8, &out);
  if (st != kOk || out.size() != 8) return -1;
  int64_t v;
  std::memcpy(&v, out.data(), 8);
  return static_cast<long>(v);
}

int pt_store_delete(void* h, const char* key) {
  std::string out;
  int st = client_rpc(static_cast<StoreClient*>(h), kDel, key, nullptr, 0,
                      &out);
  return st == kOk ? 0 : -1;
}

long pt_store_num_keys(void* h) {
  std::string out;
  int st = client_rpc(static_cast<StoreClient*>(h), kNumKeys, "", nullptr, 0,
                      &out);
  if (st != kOk || out.size() != 8) return -1;
  int64_t v;
  std::memcpy(&v, out.data(), 8);
  return static_cast<long>(v);
}

}  // extern "C"
