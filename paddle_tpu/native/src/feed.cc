// Native feed path: batch packing/stacking for the DataLoader's
// shared-memory slot rings.
//
// Reference analog: the C++ data pipeline feeding the executor
// (paddle/fluid/operators/reader/ + the DataLoader's C++ workers) — the
// copy-into-shared-memory hot loop runs native there, not in Python.
// Here: pt_feed_pack copies a batch's tensor buffers into a shm segment
// at sequential offsets (multithreaded for large batches), and
// pt_feed_stack collates equal-shape samples into one contiguous batch
// buffer — the two memcpy walls of the input pipeline.
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr uint64_t kParallelThreshold = 8ull << 20;  // 8 MiB
constexpr int kMaxThreads = 4;

void copy_range(char* dst, const char* src, uint64_t n) {
  std::memcpy(dst, src, n);
}

void parallel_copy(char* dst, const char* src, uint64_t n) {
  if (n < kParallelThreshold) {
    std::memcpy(dst, src, n);
    return;
  }
  unsigned hw = std::thread::hardware_concurrency();
  int nthreads = hw > 1 ? (hw > (unsigned)kMaxThreads ? kMaxThreads : (int)hw)
                        : 1;
  if (nthreads <= 1) {
    std::memcpy(dst, src, n);
    return;
  }
  std::vector<std::thread> ts;
  uint64_t chunk = n / nthreads;
  for (int t = 0; t < nthreads; ++t) {
    uint64_t off = (uint64_t)t * chunk;
    uint64_t len = (t == nthreads - 1) ? n - off : chunk;
    ts.emplace_back(copy_range, dst + off, src + off, len);
  }
  for (auto& th : ts) th.join();
}

}  // namespace

extern "C" {

// Copy n buffers into dst at sequential offsets. Returns total bytes.
uint64_t pt_feed_pack(const void** srcs, const uint64_t* sizes, int n,
                      void* dst) {
  uint64_t off = 0;
  for (int i = 0; i < n; ++i) {
    parallel_copy(static_cast<char*>(dst) + off,
                  static_cast<const char*>(srcs[i]), sizes[i]);
    off += sizes[i];
  }
  return off;
}

// Stack m equal-size samples contiguously into dst (the collate hot loop).
uint64_t pt_feed_stack(const void** samples, uint64_t sample_bytes, int m,
                       void* dst) {
  for (int i = 0; i < m; ++i) {
    parallel_copy(static_cast<char*>(dst) + (uint64_t)i * sample_bytes,
                  static_cast<const char*>(samples[i]), sample_bytes);
  }
  return (uint64_t)m * sample_bytes;
}

// Copy out of a shm segment (unpack side).
void pt_feed_copy(const void* src, void* dst, uint64_t nbytes) {
  parallel_copy(static_cast<char*>(dst), static_cast<const char*>(src),
                nbytes);
}

}  // extern "C"
