// Native feed path: batch packing/stacking for the DataLoader's
// shared-memory slot rings.
//
// Reference analog: the C++ data pipeline feeding the executor
// (paddle/fluid/operators/reader/ + the DataLoader's C++ workers) — the
// copy-into-shared-memory hot loop runs native there, not in Python.
// Here: pt_feed_pack copies a batch's tensor buffers into a shm segment
// at sequential offsets (multithreaded for large batches), and
// pt_feed_stack collates equal-shape samples into one contiguous batch
// buffer — the two memcpy walls of the input pipeline.
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr uint64_t kParallelThreshold = 8ull << 20;  // 8 MiB
constexpr int kMaxThreads = 4;

void copy_range(char* dst, const char* src, uint64_t n) {
  std::memcpy(dst, src, n);
}

void parallel_copy(char* dst, const char* src, uint64_t n) {
  if (n < kParallelThreshold) {
    std::memcpy(dst, src, n);
    return;
  }
  unsigned hw = std::thread::hardware_concurrency();
  int nthreads = hw > 1 ? (hw > (unsigned)kMaxThreads ? kMaxThreads : (int)hw)
                        : 1;
  if (nthreads <= 1) {
    std::memcpy(dst, src, n);
    return;
  }
  std::vector<std::thread> ts;
  uint64_t chunk = n / nthreads;
  for (int t = 0; t < nthreads; ++t) {
    uint64_t off = (uint64_t)t * chunk;
    uint64_t len = (t == nthreads - 1) ? n - off : chunk;
    ts.emplace_back(copy_range, dst + off, src + off, len);
  }
  for (auto& th : ts) th.join();
}

}  // namespace

extern "C" {

// Copy n buffers into dst at sequential offsets. Returns total bytes.
uint64_t pt_feed_pack(const void** srcs, const uint64_t* sizes, int n,
                      void* dst) {
  uint64_t off = 0;
  for (int i = 0; i < n; ++i) {
    parallel_copy(static_cast<char*>(dst) + off,
                  static_cast<const char*>(srcs[i]), sizes[i]);
    off += sizes[i];
  }
  return off;
}

// Stack m equal-size samples contiguously into dst (the collate hot loop).
uint64_t pt_feed_stack(const void** samples, uint64_t sample_bytes, int m,
                       void* dst) {
  for (int i = 0; i < m; ++i) {
    parallel_copy(static_cast<char*>(dst) + (uint64_t)i * sample_bytes,
                  static_cast<const char*>(samples[i]), sample_bytes);
  }
  return (uint64_t)m * sample_bytes;
}

// Copy out of a shm segment (unpack side).
void pt_feed_copy(const void* src, void* dst, uint64_t nbytes) {
  parallel_copy(static_cast<char*>(dst), static_cast<const char*>(src),
                nbytes);
}

// Stream variable-length token documents into fixed-capacity packed rows
// (reference analog: the data_feed.cc slot-parsing/batching hot loop; the
// varlen-flash consumer is FlashAttnUnpaddedKernel).
//
// tokens: all docs concatenated; lengths[n_docs] in tokens. Rows are cut
// at `capacity`; a document crossing a row boundary continues as a NEW
// segment in the next row (attention reset at the cut, the packed-
// pretraining convention). Per-row segment ids start at 0 and increment
// at every document (or cut) boundary; tail padding gets segment -1 and
// `pad_id` tokens. Returns rows used, or -1 if max_rows is too small.
// split_docs != 0: a document crossing a row boundary is cut (densest
// packing, attention reset at the cut). split_docs == 0: a document that
// does not fit the remaining row starts a NEW row (whole-document
// packing — the tail of the previous row becomes padding; documents
// longer than `capacity` start at a fresh row and are cut at capacity
// boundaries only).
int64_t pt_pack_varlen(const int32_t* tokens, const int64_t* lengths,
                       int64_t n_docs, int64_t capacity, int32_t pad_id,
                       int32_t* out_ids, int32_t* out_seg,
                       int64_t max_rows, int32_t split_docs) {
  int64_t row = 0, col = 0;
  int32_t seg = 0;
  const int32_t* p = tokens;
  for (int64_t d = 0; d < n_docs; ++d) {
    int64_t remaining = lengths[d];
    if (!split_docs && col > 0 && remaining > capacity - col) {
      // whole-doc mode: pad out this row and start fresh
      for (int64_t i = col; i < capacity; ++i) {
        out_ids[row * capacity + i] = pad_id;
        out_seg[row * capacity + i] = -1;
      }
      ++row;
      col = 0;
      seg = 0;
    }
    while (remaining > 0) {
      if (col == capacity) {
        ++row;
        col = 0;
        seg = 0;
      }
      if (row >= max_rows) return -1;
      int64_t take = capacity - col;
      if (remaining < take) take = remaining;
      std::memcpy(out_ids + row * capacity + col, p,
                  (size_t)take * sizeof(int32_t));
      for (int64_t i = 0; i < take; ++i) out_seg[row * capacity + col + i] = seg;
      p += take;
      col += take;
      remaining -= take;
      if (remaining > 0) {
        // document cut at the row boundary: next chunk is a new segment
        continue;
      }
      ++seg;
    }
  }
  // pad the tail of the last row
  if (col > 0 || row == 0) {
    for (int64_t i = col; i < capacity; ++i) {
      out_ids[row * capacity + i] = pad_id;
      out_seg[row * capacity + i] = -1;
    }
    ++row;
  }
  return row;
}

}  // extern "C"

extern "C" {

// Parse multi-slot text records (reference data_feed.cc
// MultiSlotDataFeed::ParseOneInstance hot loop): each line holds, per
// declared slot in order, "<count> v1 ... vcount". Values parse as
// doubles (callers cast dense float slots / integer id slots).
//
// Outputs: out_vals (all values, record-major), out_counts (n_records *
// n_slots per-slot counts). Returns the record count, or -1 if a
// capacity is exceeded, -2 on malformed input.
int64_t pt_parse_slot_lines(const char* buf, int64_t len, int64_t n_slots,
                            double* out_vals, int64_t vals_cap,
                            int32_t* out_counts, int64_t counts_cap) {
  int64_t i = 0, n_vals = 0, n_records = 0;
  while (i < len) {
    // skip blank lines
    while (i < len && (buf[i] == '\n' || buf[i] == '\r')) ++i;
    if (i >= len) break;
    if ((n_records + 1) * n_slots > counts_cap) return -1;
    for (int64_t s = 0; s < n_slots; ++s) {
      // parse count
      while (i < len && (buf[i] == ' ' || buf[i] == '\t')) ++i;
      if (i >= len || buf[i] == '\n' || buf[i] == '\r') return -2;
      int64_t cnt = 0;
      bool any = false;
      while (i < len && buf[i] >= '0' && buf[i] <= '9') {
        cnt = cnt * 10 + (buf[i] - '0');
        ++i;
        any = true;
      }
      if (!any) return -2;
      out_counts[n_records * n_slots + s] = (int32_t)cnt;
      for (int64_t v = 0; v < cnt; ++v) {
        while (i < len && (buf[i] == ' ' || buf[i] == '\t')) ++i;
        if (i >= len || buf[i] == '\n' || buf[i] == '\r') return -2;
        char* end = nullptr;
        double val = strtod(buf + i, &end);
        if (end == buf + i) return -2;
        if (n_vals >= vals_cap) return -1;
        out_vals[n_vals++] = val;
        i = end - buf;
      }
    }
    // to end of line
    while (i < len && buf[i] != '\n') ++i;
    ++n_records;
  }
  return n_records;
}

}  // extern "C"
