// Host arena allocator: best-fit-with-coalescing allocator over
// malloc'd chunks, with stats. Used for host staging buffers
// (dataloader batches headed for device transfer).
//
// Capability parity target: the reference's auto-growth best-fit
// allocator (paddle/fluid/memory/allocation/
// auto_growth_best_fit_allocator.h:30) and the AllocatorFacade stats
// (allocator_facade.h:45, stat_allocator.h). On TPU, HBM is managed by
// PJRT/XLA, so the native allocator obligation lands on the host side:
// reusable aligned staging memory without per-batch malloc/free churn.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <new>
#include <set>
#include <unordered_map>
#include <vector>

namespace {

constexpr size_t kAlign = 256;  // device-transfer friendly alignment

inline size_t align_up(size_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

struct Block {
  char* ptr;
  size_t size;
  bool free;
  Block* prev;  // address-ordered neighbors within the same chunk
  Block* next;
};

struct FreeKey {
  size_t size;
  char* ptr;
  bool operator<(const FreeKey& o) const {
    return size != o.size ? size < o.size : ptr < o.ptr;
  }
};

class Arena {
 public:
  explicit Arena(size_t chunk_size)
      : chunk_size_(chunk_size < (1 << 20) ? (1 << 20) : chunk_size) {}

  ~Arena() {
    for (char* c : chunks_) std::free(c);
    for (Block* b : all_blocks_) delete b;
  }

  void* Alloc(size_t size) {
    std::lock_guard<std::mutex> lk(mu_);
    size = align_up(size ? size : kAlign);
    auto it = free_blocks_.lower_bound(FreeKey{size, nullptr});
    if (it == free_blocks_.end()) {
      if (!Grow(size)) return nullptr;
      it = free_blocks_.lower_bound(FreeKey{size, nullptr});
      if (it == free_blocks_.end()) return nullptr;
    }
    Block* b = block_at_[it->ptr];
    free_blocks_.erase(it);
    b->free = false;
    if (b->size >= size + kAlign) {  // split the tail into a free block
      Block* tail = NewBlock(b->ptr + size, b->size - size, true, b, b->next);
      if (b->next) b->next->prev = tail;
      b->next = tail;
      b->size = size;
      free_blocks_.insert({tail->size, tail->ptr});
      block_at_[tail->ptr] = tail;
    }
    in_use_ += b->size;
    if (in_use_ > peak_) peak_ = in_use_;
    ++num_allocs_;
    live_[b->ptr] = b;
    return b->ptr;
  }

  // Returns 0 on success, -1 if ptr unknown.
  int Free(void* p) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = live_.find(static_cast<char*>(p));
    if (it == live_.end()) return -1;
    Block* b = it->second;
    live_.erase(it);
    in_use_ -= b->size;
    b->free = true;
    // Coalesce with address-adjacent free neighbors.
    if (b->next && b->next->free) Merge(b, b->next);
    if (b->prev && b->prev->free) {
      b = b->prev;
      Merge(b, b->next);
    }
    free_blocks_.insert({b->size, b->ptr});
    block_at_[b->ptr] = b;
    return 0;
  }

  // stat ids: 0=in_use 1=peak 2=reserved 3=num_allocs 4=num_chunks
  uint64_t Stat(int id) {
    std::lock_guard<std::mutex> lk(mu_);
    switch (id) {
      case 0: return in_use_;
      case 1: return peak_;
      case 2: return reserved_;
      case 3: return num_allocs_;
      case 4: return chunks_.size();
      default: return 0;
    }
  }

 private:
  Block* NewBlock(char* ptr, size_t size, bool free, Block* prev,
                  Block* next) {
    Block* b = new Block{ptr, size, free, prev, next};
    all_blocks_.push_back(b);
    return b;
  }

  // Merge b and its next neighbor (both must be in the same chunk).
  void Merge(Block* b, Block* n) {
    free_blocks_.erase({n->size, n->ptr});
    block_at_.erase(n->ptr);
    // If b is currently indexed as free, drop its stale size entry.
    free_blocks_.erase({b->size, b->ptr});
    b->size += n->size;
    b->next = n->next;
    if (n->next) n->next->prev = b;
    // n leaks into all_blocks_ until arena destruction; mark dead.
    n->ptr = nullptr;
    n->size = 0;
  }

  bool Grow(size_t min_size) {
    size_t sz = min_size > chunk_size_ ? align_up(min_size) : chunk_size_;
    char* mem = static_cast<char*>(std::aligned_alloc(kAlign, sz));
    if (!mem) return false;
    chunks_.push_back(mem);
    reserved_ += sz;
    Block* b = NewBlock(mem, sz, true, nullptr, nullptr);
    free_blocks_.insert({sz, mem});
    block_at_[mem] = b;
    return true;
  }

  std::mutex mu_;
  size_t chunk_size_;
  std::vector<char*> chunks_;
  std::vector<Block*> all_blocks_;
  std::set<FreeKey> free_blocks_;
  std::unordered_map<char*, Block*> block_at_;  // block start -> Block
  std::unordered_map<char*, Block*> live_;      // outstanding allocs
  uint64_t in_use_ = 0, peak_ = 0, reserved_ = 0, num_allocs_ = 0;
};

}  // namespace

extern "C" {

void* pt_arena_create(uint64_t chunk_size) {
  return new (std::nothrow) Arena(static_cast<size_t>(chunk_size));
}

void pt_arena_destroy(void* h) { delete static_cast<Arena*>(h); }

void* pt_arena_alloc(void* h, uint64_t size) {
  return static_cast<Arena*>(h)->Alloc(static_cast<size_t>(size));
}

int pt_arena_free(void* h, void* p) {
  return static_cast<Arena*>(h)->Free(p);
}

uint64_t pt_arena_stat(void* h, int id) {
  return static_cast<Arena*>(h)->Stat(id);
}

}  // extern "C"
