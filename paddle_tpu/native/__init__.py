"""Native C++ runtime layer for paddle_tpu.

Components (see src/):
  - TCPStore   : TCP rendezvous KV store (set/get/add/wait/barrier) used to
                 bootstrap multi-host jobs. Parity target: the reference's
                 TCPStore (paddle/phi/core/distributed/store/tcp_store.h:120).
  - HostTracer : per-thread span recording + chrome-trace export. Parity
                 target: host profiler (paddle/fluid/platform/profiler/).
  - HostArena  : best-fit coalescing host staging allocator with stats.
                 Parity target: AutoGrowthBestFitAllocator
                 (paddle/fluid/memory/allocation/).

The C++ sources are compiled on first import with g++ into a cached .so
and bound via ctypes (this image has no pybind11; ctypes is the contract).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import struct
import subprocess
import threading
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC_DIR = os.path.join(_HERE, "src")
_LIB_DIR = os.path.join(_HERE, "_lib")
_SOURCES = ("tcp_store.cc", "tracer.cc", "arena.cc", "feed.cc")

_lib = None
_lib_err: str | None = None
_build_lock = threading.Lock()


def _source_hash() -> str:
    h = hashlib.sha256()
    for name in _SOURCES:
        with open(os.path.join(_SRC_DIR, name), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def _build() -> str:
    os.makedirs(_LIB_DIR, exist_ok=True)
    so_path = os.path.join(_LIB_DIR, f"libpt_native_{_source_hash()}.so")
    if os.path.exists(so_path):
        try:  # a cached file must actually load (a racer may have
            ctypes.CDLL(so_path)  # published a corrupt link product)
            return so_path
        except OSError:
            pass
    srcs = [os.path.join(_SRC_DIR, s) for s in _SOURCES]
    # per-PID tmp: DataLoader workers may build concurrently across
    # PROCESSES (the threading lock cannot serialize them); each links its
    # own file and os.replace publishes atomically, last writer wins
    tmp = so_path + f".{os.getpid()}.tmp"
    cmd = [
        "g++", "-std=c++17", "-O2", "-fPIC", "-shared", "-pthread",
        "-Wall", *srcs, "-o", tmp,
    ]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    os.replace(tmp, so_path)
    return so_path


def _bind(lib: ctypes.CDLL) -> None:
    c = ctypes
    # store
    lib.pt_store_server_start.argtypes = [c.c_int, c.POINTER(c.c_int)]
    lib.pt_store_server_start.restype = c.c_void_p
    lib.pt_store_server_stop.argtypes = [c.c_void_p]
    lib.pt_store_client_connect.argtypes = [c.c_char_p, c.c_int, c.c_int]
    lib.pt_store_client_connect.restype = c.c_void_p
    lib.pt_store_client_close.argtypes = [c.c_void_p]
    lib.pt_store_set.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p, c.c_uint32]
    lib.pt_store_set.restype = c.c_int
    lib.pt_store_get.argtypes = [
        c.c_void_p, c.c_char_p, c.c_void_p, c.c_uint32, c.c_int,
    ]
    lib.pt_store_get.restype = c.c_long
    lib.pt_store_add.argtypes = [c.c_void_p, c.c_char_p, c.c_long]
    lib.pt_store_add.restype = c.c_long
    lib.pt_store_wait_ge.argtypes = [c.c_void_p, c.c_char_p, c.c_long]
    lib.pt_store_wait_ge.restype = c.c_long
    lib.pt_store_delete.argtypes = [c.c_void_p, c.c_char_p]
    lib.pt_store_delete.restype = c.c_int
    lib.pt_store_num_keys.argtypes = [c.c_void_p]
    lib.pt_store_num_keys.restype = c.c_long
    # tracer
    lib.pt_trace_enable.argtypes = [c.c_int]
    lib.pt_trace_enabled.restype = c.c_int
    lib.pt_trace_push.argtypes = [c.c_char_p]
    lib.pt_trace_pop.argtypes = []
    lib.pt_trace_span.argtypes = [c.c_char_p, c.c_uint64, c.c_uint64]
    lib.pt_trace_counter.argtypes = [c.c_char_p, c.c_double]
    lib.pt_trace_now_ns.restype = c.c_uint64
    lib.pt_trace_num_spans.restype = c.c_long
    lib.pt_trace_dump.argtypes = [c.c_char_p]
    lib.pt_trace_dump.restype = c.c_int
    lib.pt_trace_get_span.argtypes = [
        c.c_long, c.c_char_p, c.c_int, c.POINTER(c.c_uint64),
        c.POINTER(c.c_uint64), c.POINTER(c.c_int64),
    ]
    lib.pt_trace_get_span.restype = c.c_int
    # feed (native data-pipeline copies)
    lib.pt_feed_pack.argtypes = [
        c.POINTER(c.c_void_p), c.POINTER(c.c_uint64), c.c_int, c.c_void_p,
    ]
    lib.pt_feed_pack.restype = c.c_uint64
    lib.pt_feed_stack.argtypes = [
        c.POINTER(c.c_void_p), c.c_uint64, c.c_int, c.c_void_p,
    ]
    lib.pt_feed_stack.restype = c.c_uint64
    lib.pt_feed_copy.argtypes = [c.c_void_p, c.c_void_p, c.c_uint64]
    lib.pt_pack_varlen.argtypes = [
        c.c_void_p, c.c_void_p, c.c_int64, c.c_int64, c.c_int32,
        c.c_void_p, c.c_void_p, c.c_int64, c.c_int32,
    ]
    lib.pt_pack_varlen.restype = c.c_int64
    lib.pt_parse_slot_lines.argtypes = [
        c.c_char_p, c.c_int64, c.c_int64, c.c_void_p, c.c_int64,
        c.c_void_p, c.c_int64,
    ]
    lib.pt_parse_slot_lines.restype = c.c_int64
    # arena
    lib.pt_arena_create.argtypes = [c.c_uint64]
    lib.pt_arena_create.restype = c.c_void_p
    lib.pt_arena_destroy.argtypes = [c.c_void_p]
    lib.pt_arena_alloc.argtypes = [c.c_void_p, c.c_uint64]
    lib.pt_arena_alloc.restype = c.c_void_p
    lib.pt_arena_free.argtypes = [c.c_void_p, c.c_void_p]
    lib.pt_arena_free.restype = c.c_int
    lib.pt_arena_stat.argtypes = [c.c_void_p, c.c_int]
    lib.pt_arena_stat.restype = c.c_uint64


def get_lib() -> ctypes.CDLL:
    """Build (once) and return the native library, raising on failure."""
    global _lib, _lib_err
    if _lib is not None:
        return _lib
    if _lib_err is not None:
        raise RuntimeError(f"paddle_tpu native library unavailable: {_lib_err}")
    with _build_lock:
        if _lib is not None:
            return _lib
        try:
            so = _build()
            lib = ctypes.CDLL(so)
            _bind(lib)
            _lib = lib
        except Exception as e:  # noqa: BLE001 — record and surface once
            _lib_err = repr(e)
            raise RuntimeError(
                f"paddle_tpu native library unavailable: {_lib_err}"
            ) from e
    return _lib


def available() -> bool:
    try:
        get_lib()
        return True
    except RuntimeError:
        return False


# ---------------------------------------------------------------------------
# TCPStore


class TCPStore:
    """TCP rendezvous store. The master rank hosts the server; every rank
    (master included) talks to it through a client connection.

    API mirrors the reference store semantics: set/get are byte-valued,
    add() is an atomic counter, wait() blocks until a key exists, and
    barrier() is an add + wait-ge rendezvous.

    The blocking entry points (get / wait_ge / barrier) take the same
    ``timeout_s`` keyword as distributed.env.InProcStore and raise
    TimeoutError with the same diagnostics — the two stores are
    interchangeable behind one contract (tests/test_store_contract.py).
    Timeouts are implemented client-side by polling the non-blocking
    primitives: the C++ server parks blocking requests forever, and a
    parked request cannot be cancelled without tearing down the
    connection, so the wrapper never issues an unbounded blocking RPC.
    """

    _POLL_S = 0.005  # client-side poll interval for timed blocking ops

    def __init__(self, host: str, port: int, *, is_master: bool = False,
                 world_size: int = 1, timeout_s: float = 60.0,
                 connect_attempts: int = 3):
        lib = get_lib()
        self._lib = lib
        self._server = None
        self.world_size = world_size
        if is_master:
            bound = ctypes.c_int(0)
            self._server = lib.pt_store_server_start(port, ctypes.byref(bound))
            if not self._server:
                raise RuntimeError(f"TCPStore: cannot bind port {port}")
            port = bound.value
        self.host, self.port = host, port
        connect_host = "127.0.0.1" if is_master else host

        # transient connect failures (master not bound yet, connection
        # refused during a rolling restart) retry under the shared policy;
        # the deadline caps the TOTAL wait at the caller's timeout
        from ..resilience.retry import RetryError, RetryPolicy

        def _connect():
            client = lib.pt_store_client_connect(
                connect_host.encode(), port, int(timeout_s * 1000))
            if not client:
                raise ConnectionError(
                    f"TCPStore: cannot connect to {host}:{port}")
            return client

        policy = RetryPolicy(max_attempts=connect_attempts, base_delay=0.05,
                             max_delay=1.0, deadline=timeout_s,
                             retry_on=(ConnectionError,),
                             name="tcpstore.connect")
        try:
            self._client = policy.call(_connect)
        except (RetryError, ConnectionError) as e:
            if self._server:
                lib.pt_store_server_stop(self._server)
                self._server = None
            raise RuntimeError(
                f"TCPStore: cannot connect to {host}:{port}") from e

    def set(self, key: str, value: bytes | str) -> None:
        if isinstance(value, str):
            value = value.encode()
        rc = self._lib.pt_store_set(self._client, key.encode(), value,
                                    len(value))
        if rc != 0:
            raise RuntimeError("TCPStore.set failed")

    def _get_once(self, key: str) -> bytes | None:
        """One non-blocking fetch; None when the key is missing."""
        cap = 1 << 20
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.pt_store_get(self._client, key.encode(), buf, cap, 0)
            if n == -2:
                return None
            if n < 0:
                raise RuntimeError("TCPStore.get failed")
            if n <= cap:
                return buf.raw[: int(n)]
            # value larger than the buffer: refetch with an exactly-sized
            # buffer (the key exists now)
            cap = int(n)

    def get(self, key: str, *, blocking: bool = True,
            timeout_s: float = 60.0) -> bytes | None:
        v = self._get_once(key)
        if v is not None or not blocking:
            return v
        deadline = time.monotonic() + float(timeout_s)
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"TCPStore.get({key!r}) timed out "
                                   f"after {float(timeout_s):g}s")
            time.sleep(min(self._POLL_S, max(remaining, 0.0)))
            v = self._get_once(key)
            if v is not None:
                return v

    def add(self, key: str, delta: int = 1) -> int:
        v = self._lib.pt_store_add(self._client, key.encode(), delta)
        if v == -1:
            raise RuntimeError("TCPStore.add failed")
        return int(v)

    def _counter(self, key: str) -> int:
        """Read a counter without creating it: counters are stored as one
        packed native int64 (tcp_store.cc kAdd); a missing key is 0."""
        raw = self._get_once(key)
        if raw is None:
            return 0
        if len(raw) == 8:
            return int(struct.unpack("<q", raw)[0])
        try:  # a set() may have overwritten the counter with text
            return int(raw.decode())
        except (UnicodeDecodeError, ValueError):
            return 0

    def wait_ge(self, key: str, target: int, *,
                timeout_s: float = 60.0) -> int:
        deadline = time.monotonic() + float(timeout_s)
        while True:
            cur = self._counter(key)
            if cur >= int(target):
                return cur
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"TCPStore.wait_ge({key!r}, {target}) timed out "
                    f"after {float(timeout_s):g}s: counter at {cur}, "
                    f"{int(target) - cur} arrival(s) never happened")
            time.sleep(min(self._POLL_S, max(remaining, 0.0)))

    def delete(self, key: str) -> None:
        self._lib.pt_store_delete(self._client, key.encode())

    def num_keys(self) -> int:
        return int(self._lib.pt_store_num_keys(self._client))

    def barrier(self, name: str | None = None,
                world_size: int | None = None, *,
                rank: int | None = None,
                timeout_s: float = 60.0) -> None:
        """Rendezvous of `world_size` callers. Client-STATELESS wave
        counting (same scheme as InProcStore.barrier): the n-th arrival
        belongs to wave ceil(n/world) and waits for that wave to fill, so
        a reused name re-rendezvouses correctly and a reconnected client
        carries no barrier generation to lose.

        With `rank` given, a timeout names the ranks whose arrival key
        never appeared for this wave instead of just "timed out"."""
        world = int(world_size or self.world_size)
        if name is None:
            name = "__anon"
        n = self.add(f"/barrier/{name}", 1)
        wave = (n + world - 1) // world
        if rank is not None:
            self.set(f"/barrier/{name}/w{wave}/r{int(rank)}", b"1")
        try:
            self.wait_ge(f"/barrier/{name}", world * wave,
                         timeout_s=timeout_s)
        except TimeoutError:
            arrived = self._counter(f"/barrier/{name}") - world * (wave - 1)
            msg = (f"TCPStore.barrier({name!r}) timed out after "
                   f"{float(timeout_s):g}s: {arrived}/{world} callers "
                   f"arrived in wave {wave}")
            if rank is not None:
                missing = [r for r in range(world)
                           if self._get_once(
                               f"/barrier/{name}/w{wave}/r{r}") is None]
                if missing:
                    msg += (f"; ranks whose arrival key never appeared: "
                            f"{missing}")
            raise TimeoutError(msg) from None

    def close(self) -> None:
        if getattr(self, "_client", None):
            self._lib.pt_store_client_close(self._client)
            self._client = None
        if getattr(self, "_server", None):
            self._lib.pt_store_server_stop(self._server)
            self._server = None

    def __del__(self):  # noqa: D105
        try:
            self.close()
        except Exception:  # noqa: BLE001,S110 — interpreter teardown
            pass


# ---------------------------------------------------------------------------
# Host tracer


def trace_enable(on: bool = True) -> None:
    get_lib().pt_trace_enable(1 if on else 0)


def trace_enabled() -> bool:
    return bool(get_lib().pt_trace_enabled())


def trace_push(name: str) -> None:
    get_lib().pt_trace_push(name.encode())


def trace_pop() -> None:
    get_lib().pt_trace_pop()


def trace_span(name: str, begin_ns: int, end_ns: int) -> None:
    get_lib().pt_trace_span(name.encode(), begin_ns, end_ns)


def trace_counter(name: str, value: float) -> None:
    get_lib().pt_trace_counter(name.encode(), float(value))


def trace_now_ns() -> int:
    return int(get_lib().pt_trace_now_ns())


def trace_clear() -> None:
    get_lib().pt_trace_clear()


def trace_num_spans() -> int:
    return int(get_lib().pt_trace_num_spans())


def trace_dump(path: str) -> None:
    rc = get_lib().pt_trace_dump(path.encode())
    if rc != 0:
        raise RuntimeError(f"trace_dump({path}) failed")


def trace_spans() -> list[dict]:
    """Return all recorded spans as dicts (name/begin_ns/end_ns/tid)."""
    lib = get_lib()
    out = []
    name = ctypes.create_string_buffer(256)
    b = ctypes.c_uint64()
    e = ctypes.c_uint64()
    t = ctypes.c_int64()
    for i in range(trace_num_spans()):
        if lib.pt_trace_get_span(i, name, 256, ctypes.byref(b),
                                 ctypes.byref(e), ctypes.byref(t)) == 0:
            out.append({
                "name": name.value.decode(errors="replace"),
                "begin_ns": b.value, "end_ns": e.value, "tid": t.value,
            })
    return out


class TraceScope:
    """Context manager recording one host span, usable from Python."""

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        trace_push(self.name)
        return self

    def __exit__(self, *exc):
        trace_pop()
        return False


# ---------------------------------------------------------------------------
# Host arena allocator


class HostArena:
    """Best-fit coalescing arena over malloc'd chunks, for staging buffers.

    stats(): in_use / peak / reserved / num_allocs / num_chunks (bytes).
    numpy(shape, dtype) hands out a numpy array backed by arena memory;
    call free(arr) when the batch has been shipped to device.
    """

    _STATS = ("in_use", "peak", "reserved", "num_allocs", "num_chunks")

    def __init__(self, chunk_size: int = 64 << 20):
        self._lib = get_lib()
        self._h = self._lib.pt_arena_create(chunk_size)
        if not self._h:
            raise MemoryError("HostArena: create failed")
        self._owned: dict[int, int] = {}  # array data ptr -> raw ptr

    def alloc(self, size: int) -> int:
        p = self._lib.pt_arena_alloc(self._h, size)
        if not p:
            raise MemoryError(f"HostArena: alloc({size}) failed")
        return p

    def free(self, obj) -> None:
        import numpy as np

        if isinstance(obj, np.ndarray):
            ptr = obj.ctypes.data
            raw = self._owned.pop(ptr, ptr)
        else:
            raw = int(obj)
        if self._lib.pt_arena_free(self._h, raw) != 0:
            raise ValueError("HostArena.free: unknown pointer")

    def numpy(self, shape, dtype):
        import numpy as np

        dtype = np.dtype(dtype)
        n = int(np.prod(shape)) * dtype.itemsize
        ptr = self.alloc(max(n, 1))
        ctype_arr = (ctypes.c_char * max(n, 1)).from_address(ptr)
        arr = np.frombuffer(ctype_arr, dtype=dtype, count=int(np.prod(shape)))
        arr = arr.reshape(shape)
        self._owned[arr.ctypes.data] = ptr
        return arr

    def stats(self) -> dict[str, int]:
        return {
            name: int(self._lib.pt_arena_stat(self._h, i))
            for i, name in enumerate(self._STATS)
        }

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.pt_arena_destroy(self._h)
            self._h = None

    def __del__(self):  # noqa: D105
        try:
            self.close()
        except Exception:  # noqa: BLE001,S110 — interpreter teardown
            pass


# ---- native feed path (reference: the C++ reader/data pipeline) -----------
def feed_pack(arrays, dst_buf) -> int:
    """Copy `arrays` (contiguous numpy) into `dst_buf` (writable buffer,
    e.g. a SharedMemory.buf) at sequential offsets with one native call.
    Returns total bytes written."""
    import numpy as np

    lib = get_lib()
    n = len(arrays)
    srcs = (ctypes.c_void_p * n)()
    sizes = (ctypes.c_uint64 * n)()
    keepalive = []
    total = 0
    for i, a in enumerate(arrays):
        a = np.ascontiguousarray(a)
        keepalive.append(a)
        srcs[i] = a.ctypes.data
        sizes[i] = a.nbytes
        total += a.nbytes
    if total > len(dst_buf):
        raise ValueError(
            f"feed_pack: {total} bytes do not fit the {len(dst_buf)}-byte "
            "destination buffer")
    dst = (ctypes.c_char * len(dst_buf)).from_buffer(dst_buf)
    return int(lib.pt_feed_pack(srcs, sizes, n, ctypes.addressof(dst)))


def feed_stack(samples, out) -> None:
    """Collate equal-shape samples into the preallocated `out` batch array
    (out.shape[0] == len(samples)) with one native call."""
    import numpy as np

    lib = get_lib()
    m = len(samples)
    ptrs = (ctypes.c_void_p * m)()
    keepalive = []
    for i, s in enumerate(samples):
        s = np.ascontiguousarray(s)
        if s.shape != samples[0].shape or s.dtype != samples[0].dtype:
            raise ValueError(
                "feed_stack: samples must share shape/dtype "
                f"(sample {i}: {s.shape}/{s.dtype} vs "
                f"{samples[0].shape}/{samples[0].dtype})")
        keepalive.append(s)
        ptrs[i] = s.ctypes.data
    if not out.flags.c_contiguous or out.shape[0] != m \
            or out.nbytes != m * keepalive[0].nbytes:
        raise ValueError(
            "feed_stack: out must be C-contiguous [m, *sample.shape] "
            f"(got shape {out.shape}, nbytes {out.nbytes})")
    lib.pt_feed_stack(ptrs, keepalive[0].nbytes, m,
                      out.ctypes.data_as(ctypes.c_void_p))


def feed_copy_out(buf, offset, shape, dtype):
    """Copy a packed region out of a shm buffer into a fresh array."""
    import numpy as np

    lib = get_lib()
    out = np.empty(shape, dtype)
    base = ctypes.addressof((ctypes.c_char * len(buf)).from_buffer(buf))
    lib.pt_feed_copy(ctypes.c_void_p(base + offset),
                     out.ctypes.data_as(ctypes.c_void_p), out.nbytes)
    return out


def pack_varlen(docs, capacity: int, pad_id: int = 0,
                split_docs: bool = True):
    """Stream variable-length int32 token docs into packed fixed rows
    (native hot loop; see feed.cc pt_pack_varlen). Returns
    (ids [rows, capacity] int32, segments [rows, capacity] int32) where
    padding has segment -1 and documents cut at row boundaries continue
    as new segments."""
    import numpy as np

    lib = get_lib()
    docs = [np.ascontiguousarray(d, np.int32).ravel() for d in docs]
    lengths = np.asarray([len(d) for d in docs], np.int64)
    tokens = (np.concatenate(docs) if docs
              else np.zeros(0, np.int32)).astype(np.int32)
    total = int(lengths.sum())
    max_rows = max(1, (total + capacity - 1) // capacity + 1
                   + (0 if split_docs else len(docs)))
    ids = np.full((max_rows, capacity), pad_id, np.int32)
    seg = np.full((max_rows, capacity), -1, np.int32)
    rows = int(lib.pt_pack_varlen(
        tokens.ctypes.data_as(ctypes.c_void_p),
        lengths.ctypes.data_as(ctypes.c_void_p),
        len(docs), capacity, pad_id,
        ids.ctypes.data_as(ctypes.c_void_p),
        seg.ctypes.data_as(ctypes.c_void_p), max_rows,
        1 if split_docs else 0))
    if rows < 0:
        raise ValueError("pack_varlen: row buffer too small (internal)")
    return ids[:rows], seg[:rows]


def parse_slot_lines(data: bytes, n_slots: int):
    """Parse multi-slot text records natively (see feed.cc). Returns
    (values f64 [n_vals], counts i32 [n_records, n_slots])."""
    import numpy as np

    lib = get_lib()
    # a value needs >= 2 bytes of text; counts need >= 2 per slot field
    vals_cap = max(16, len(data) // 2 + 1)
    # each record line carries n_slots count tokens of >= 2 bytes, so
    # n_records <= len//(2*n_slots)+1; cap = that times n_slots
    counts_cap = max(16 * n_slots, len(data) // 2 + n_slots)
    vals = np.empty(vals_cap, np.float64)
    counts = np.empty(counts_cap, np.int32)
    n = int(lib.pt_parse_slot_lines(
        data, len(data), n_slots,
        vals.ctypes.data_as(ctypes.c_void_p), vals_cap,
        counts.ctypes.data_as(ctypes.c_void_p), counts_cap))
    if n == -1:
        raise ValueError("parse_slot_lines: capacity exceeded (internal)")
    if n == -2:
        raise ValueError("parse_slot_lines: malformed multi-slot record")
    counts = counts[:n * n_slots].reshape(n, n_slots).copy()
    return vals[:int(counts.sum())].copy(), counts  # drop the big arenas
