"""Serving-side observability: request traces, SLO metrics, flight arm.

The training stack got a full observability layer in r9/r10 (telemetry,
metrics registry, spans, anomaly engine, flight recorder); the serving
engine grew to production shape with only ad-hoc `/stats` dicts. This
module closes that gap — it is the telemetry substrate the multi-replica
fleet/router work consumes (SLO-aware admission and shedding need
per-request TTFT/TPOT/goodput, not aggregate averages):

  * ``RequestTrace`` — per-request lifecycle spans (queue-wait, admission,
    each prefill chunk, decode ticks, speculative verify, rollback,
    finish/cancel) recorded through the process-wide ``observability.spans``
    ring, so a profiler fallback session (``profiler.Profiler``) collects
    them into its chrome-trace export automatically; ``export_request_trace``
    writes one request's own spans as a standalone chrome trace.
  * SLO metrics on the shared registry, labeled by admission ``tier``
    (one tier today — "default" — the label is the seam the router's
    priority classes plug into): TTFT, TPOT (mean inter-token latency),
    queue time and e2e latency histograms; goodput token and shed request
    counters. All ``always=True`` like the rest of the serving_* family —
    serving runs don't require FLAGS_metrics.
  * Engine gauges sampled every TICK_SAMPLE engine ticks
    (FLAGS_metrics-gated — the metrics-off tick path stays a
    two-attribute no-op): slot occupancy,
    batch size, rolling prefix-cache hit rate, speculative acceptance.
    Block-pool live/evictable/free gauges are published by the allocator
    itself (blocks.py, always on).
  * A serving flight-recorder arm: bounded rings of finished request
    records (telemetry + trace) and engine tick snapshots, auto-dumped
    through the SAME ``flight_recorder.dump`` path as training (one
    naming/dir scheme under FLAGS_metrics_dir/flight) when a serving
    anomaly detector fires — TTFT regression, goodput collapse, cache-hit
    collapse, allocator conservation breach (observability/anomaly.py,
    same rolling-window engine as the training detectors).

Everything here is host-side and engine-lock-friendly: hooks are invoked
by the engine while it already holds ``engine._lock``, and the only
cross-thread readers (the HTTP handlers) go through snapshot methods.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..core.flags import define_flag, get_flag
from ..observability import anomaly as _anomaly
from ..observability import flight_recorder as _flight
from ..observability import spans as _spans
from ..observability.registry import (
    counter as _counter,
    gauge as _gauge,
    histogram as _histogram,
    metrics_enabled,
)

define_flag("serving_metrics_port", 0,
            "Also serve the process-wide GET /metrics (Prometheus text) + "
            "/healthz on this dedicated port from the serving process "
            "(observability/serve.py machinery); 0 disables. The "
            "ServingServer's own port always answers GET /metrics and "
            "/healthz regardless.")
define_flag("serving_flight_requests", 64,
            "Serving flight-recorder arm: how many finished request "
            "records (telemetry + trace) and engine tick snapshots ride "
            "along in an anomaly dump.")
define_flag("serving_anomaly", "auto",
            "Serving anomaly detectors (TTFT regression, goodput collapse, "
            "cache-hit collapse, KV conservation breach) over per-tick "
            "records: 'auto' follows FLAGS_anomaly, 'on'/'off' override it. "
            "Needs FLAGS_metrics=on either way.")

_TRUE = ("1", "on", "true", "yes")

#: healthz: engine has work but no tick for this long => status "stale"
STALE_AFTER_S = 60.0
#: healthz: anomalies within this window => status "anomalous"
ANOMALY_RECENT_S = 300.0

# ---------------------------------------------------------------- metrics
# SLO histograms/counters are labeled by admission tier ("default" until
# the router's priority classes land) and always=True like every other
# serving_* metric: the legacy /stats contract predates FLAGS_metrics.
_TTFT_H = _histogram("serving_ttft_seconds",
                     "Arrival -> first token, per request.",
                     labelnames=("tier",), always=True)
_QUEUE_H = _histogram("serving_queue_seconds",
                      "Arrival -> prefill start, per request.",
                      labelnames=("tier",), always=True)
_TPOT_H = _histogram("serving_tpot_seconds",
                     "Mean inter-token latency (time per output token "
                     "after the first), per request.",
                     labelnames=("tier",), always=True)
_E2E_H = _histogram("serving_e2e_seconds",
                    "Arrival -> finish, per request.",
                    labelnames=("tier",), always=True)
_TOKRATE_H = _histogram("serving_decode_tokens_per_s",
                        "Per-request steady-state decode rate.",
                        labelnames=("tier",), always=True)
_GEN_TOKENS = _counter("serving_generated_tokens_total",
                       "Tokens generated across all requests.", always=True)
_PREFILL_TOKENS = _counter("serving_prefill_tokens_total",
                           "Prompt tokens actually computed by prefill "
                           "(cache hits skip theirs).", always=True)
_GOODPUT_TOKENS = _counter("serving_goodput_tokens_total",
                           "Tokens delivered by requests that finished "
                           "normally (stop/length) — shed, cancelled and "
                           "timed-out work excluded.",
                           labelnames=("tier",), always=True)
_SHED = _counter("serving_shed_requests_total",
                 "Requests evicted before normal completion, by reason "
                 "(timeout, disconnect, cancelled, shed).",
                 labelnames=("tier", "reason"), always=True)

# per-tick engine gauges: FLAGS_metrics-gated (stats() is the always-on
# view of the same numbers)
_SLOT_OCC = _gauge("serving_slot_occupancy",
                   "Running sequences / decode slots, sampled per tick.")
_BATCH = _gauge("serving_batch_size",
                "Sequences in the decode batch, sampled per tick.")
_HIT_RATE = _gauge("serving_prefix_hit_rate",
                   "Rolling prefix-cache hit rate (cached prompt tokens / "
                   "prompt tokens over recent admissions).")
_SPEC_ACC = _gauge("serving_spec_acceptance",
                   "Cumulative speculative acceptance (accepted / "
                   "proposed draft tokens), sampled per tick.")
_GOODPUT_G = _gauge("serving_goodput_tokens_per_s",
                    "Decoded tokens per second over the recent tick "
                    "window, sampled per tick.")

#: finish reasons that count as delivered work (everything else is shed).
#: "prefill_complete" is the disaggregated prefill-only finish: the KV it
#: computed is the product, not the (zero) output tokens.
_GOOD_REASONS = ("stop", "length", "prefill_complete")

_ENGINE_SEQ = itertools.count()


def new_engine_id() -> str:
    """Unique per-process engine label for serving_engine_events_total."""
    return f"engine{next(_ENGINE_SEQ)}"

_ENGINE_EVENTS = _counter(
    "serving_engine_events_total",
    "Per-engine serving counters (prefill dispatches/tokens, cache "
    "admissions, speculation ticks), labeled by engine instance — the "
    "registry backing for ServingEngine's historical int attributes "
    "(thin views, same pattern as autotune._STATS).",
    labelnames=("engine", "event"), always=True)


class EngineStats:
    """Dict-shaped thin view over serving_engine_events_total{engine=...}.

    ServingEngine's historical counter attributes (prefill_programs,
    cow_admissions, ...) read through this, so one registry snapshot /
    Prometheus scrape carries every engine's counters while `/stats` and
    the bench deltas keep their int semantics. Per-engine label keeps
    engines isolated (tests build several engines per process)."""

    _KEYS = ("prefill_programs", "batched_prefills", "prefill_tokens",
             "cow_admissions", "dedup_admissions", "spec_ticks",
             "spec_proposed", "spec_accepted", "spec_rollbacks")

    __slots__ = ("_eid",)

    def __init__(self, engine_id: str):
        self._eid = str(engine_id)

    def inc(self, key: str, amount: int = 1) -> None:
        if key not in self._KEYS:
            raise KeyError(key)
        _ENGINE_EVENTS.inc(amount, engine=self._eid, event=key)

    def __getitem__(self, key: str) -> int:
        if key not in self._KEYS:
            raise KeyError(key)
        return int(_ENGINE_EVENTS.value(engine=self._eid, event=key))

    def __iter__(self):
        return iter(self._KEYS)

    def as_dict(self) -> Dict[str, int]:
        return {k: self[k] for k in self._KEYS}


def serving_anomaly_on() -> bool:
    """Serving detectors run when FLAGS_metrics=on and FLAGS_serving_anomaly
    says so ('auto' defers to FLAGS_anomaly)."""
    if not metrics_enabled():
        return False
    mode = str(get_flag("serving_anomaly")).lower()
    if mode in _TRUE:
        return True
    if mode == "auto":
        return str(get_flag("anomaly")).lower() in _TRUE
    return False


class RequestTrace:
    """Per-request span list, mirrored into the global spans ring.

    Attached to a Request at submit when span recording is enabled
    (FLAGS_metrics=on or an open profiler fallback session). Request-scoped
    spans go through ``add`` (ring + local list); batch-scoped spans the
    engine records once for everyone land in each participant's list via
    ``note`` without re-recording. Bounded so one long-running request
    cannot grow without bound."""

    MAX_SPANS = 1024

    __slots__ = ("request_id", "tier", "spans", "ctx", "slot")

    def __init__(self, request_id: str, tier: str = "default",
                 ctx: Optional[Dict[str, Any]] = None):
        self.request_id = str(request_id)
        self.tier = str(tier)
        # fleet trace context (fleet_request_id / attempt / cause) stamped
        # by the router at dispatch: baked into every request-scoped
        # span's args so a cross-replica merge needs no re-tagging.
        # Batch-scoped spans (shared dict, see on_decode) are tagged at
        # export time on copies instead.
        self.ctx = dict(ctx) if ctx else None
        # decode slot, captured at admission (the scheduler clears
        # req.slot at finish; the merged fleet trace wants tid=slot)
        self.slot: Optional[int] = None
        self.spans: deque = deque(maxlen=self.MAX_SPANS)

    def _span(self, name: str, begin_ns: int, end_ns: int,
              **args) -> Dict[str, Any]:
        base = {"request_id": self.request_id}
        if self.ctx:
            base.update(self.ctx)
        base.update(args)
        return {"name": str(name), "begin_ns": int(begin_ns),
                "end_ns": int(end_ns), "cat": "serving",
                "tid": threading.get_ident() & 0xFFFF,
                "args": base}

    def add(self, name: str, begin_ns: int, end_ns: int, **args) -> None:
        """Record a request-scoped span (local list + global ring)."""
        d = self._span(name, begin_ns, end_ns, **args)
        self.spans.append(d)
        _spans.record_span(name, begin_ns, end_ns, cat="serving",
                           args=d["args"])

    def note(self, name: str, begin_ns: int, end_ns: int, **args) -> None:
        """Attach a batch-scoped span (already in the ring) to this
        request's list only."""
        self.spans.append(self._span(name, begin_ns, end_ns, **args))

    def names(self) -> List[str]:
        return [s["name"] for s in self.spans]


def chrome_trace_events(span_dicts, *, pid: Optional[int] = None,
                        tid: Optional[int] = None,
                        extra_args: Optional[Dict[str, Any]] = None
                        ) -> List[Dict[str, Any]]:
    """Convert ring-format span dicts to chrome-trace complete events
    (the same event shape profiler/xplane.py merges).

    Every event gets its OWN args dict (deep-copied from the span): the
    engine appends one shared per-tick span dict by reference to every
    traced participant (on_decode), so tagging export-time fields on the
    original would corrupt every other request's trace. `pid`/`tid`
    override the lane (the fleet merge maps pid=replica, tid=slot);
    `extra_args` fills attribution keys (attempt/cause) without
    clobbering anything the span already carries."""
    default_pid = os.getpid() if pid is None else pid
    out = []
    for s in span_dicts:
        begin = int(s.get("begin_ns", 0))
        args = dict(s.get("args") or {})
        if extra_args:
            for k, v in extra_args.items():
                args.setdefault(k, v)
        out.append({"name": s.get("name", "?"), "ph": "X",
                    "cat": s.get("cat", "serving"),
                    "ts": begin / 1e3,
                    "dur": max(int(s.get("end_ns", begin)) - begin, 0) / 1e3,
                    "pid": default_pid,
                    "tid": s.get("tid", 0) if tid is None else tid,
                    "args": args})
    return out


def export_request_trace(req, path: str) -> str:
    """Write one request's lifecycle spans as a standalone chrome trace
    (chrome://tracing / Perfetto). ``req`` is a Request with an attached
    trace, or a RequestTrace directly. Raises ValueError when the request
    was never traced (metrics were off at submit)."""
    trace = req if isinstance(req, RequestTrace) else getattr(req, "trace",
                                                              None)
    if trace is None:
        raise ValueError("request has no trace (was FLAGS_metrics on when "
                         "it was submitted?)")
    payload = {"traceEvents": chrome_trace_events(list(trace.spans)),
               "displayTimeUnit": "ms"}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f)
    return path


class ServingObservability:
    """Per-engine observability hub: the engine calls the ``on_*`` hooks
    under its own lock; HTTP handlers read through ``health_snapshot``.

    Cheap when FLAGS_metrics is off: ``tick_begin``/``on_tick`` reduce to
    a flag check + one attribute write, traces are never attached, and the
    SLO histogram observes (always-on by contract) were already paid by
    the pre-r16 engine."""

    #: samples in the rolling goodput window
    GOODPUT_WINDOW = 16
    #: recent admissions in the rolling prefix-hit-rate window
    ADMIT_WINDOW = 64
    #: gauge/record sampling stride: the tick hot path only accumulates
    #: decoded-token counts; gauges, the tick snapshot, and the anomaly
    #: detectors run every TICK_SAMPLE-th engine step (the <=3% servebench
    #: overhead budget rules out per-tick dict/registry work)
    TICK_SAMPLE = 4

    def __init__(self, engine, *, dump: bool = True,
                 dump_cooldown_steps: int = 50):
        self.engine = engine
        self.dump = bool(dump)
        self.dump_cooldown_steps = int(dump_cooldown_steps)
        n = max(int(get_flag("serving_flight_requests")), 1)
        self._records: deque = deque(maxlen=n)   # finished request records
        self._ticks: deque = deque(maxlen=n)     # engine tick snapshots
        self._tok_window: deque = deque(maxlen=self.GOODPUT_WINDOW)
        self._admit_window: deque = deque(maxlen=self.ADMIT_WINDOW)
        self._admit_matched = 0   # running sums over _admit_window
        self._admit_total = 0
        self._decoded_acc = 0     # decoded tokens since the last sample
        self._tick_n = 0          # sampling stride counter (first tick
        #                           always samples: short runs still
        #                           produce a snapshot + anomaly record)
        self._ttft_acc: List[float] = []
        self._on = False          # metrics enabled, refreshed per tick
        self._trace_on = False    # span recording enabled, per tick
        self._anomaly: Optional[_anomaly.AnomalyEngine] = None
        self._dump_armed_at = -1
        self.last_tick_ts: Optional[float] = None
        self.dumps: List[str] = []

    def now(self) -> Optional[int]:
        """Span start timestamp, or None when nothing records this tick
        (the engine brackets its dispatches with now()/on_* pairs; a None
        t0 makes the matching hook a no-op)."""
        return time.monotonic_ns() if self._trace_on else None

    # -- request lifecycle hooks (engine lock held) ------------------------
    def on_submit(self, req) -> None:
        if _spans.enabled():
            req.trace = RequestTrace(req.request_id, req.tier,
                                     ctx=getattr(req, "trace_ctx", None))

    def on_shed(self, req, reason: str) -> None:
        """Request rejected at admission (never entered the queue): shed
        accounting only — no trace, no SLO samples, it did no work."""
        _SHED.inc(tier=req.tier, reason=str(reason))

    def on_admitted(self, req) -> None:
        """Queued -> prefill: close the queue-wait span, feed the rolling
        prefix-hit window (running sums — the tick path must not re-sum
        the window)."""
        m, p = req.prefix_matched, len(req.prompt)
        w = self._admit_window
        if len(w) == w.maxlen:
            om, op = w[0]
            self._admit_matched -= om
            self._admit_total -= op
        w.append((m, p))
        self._admit_matched += m
        self._admit_total += p
        tr = req.trace
        if tr is not None:
            tr.slot = req.slot
            if req.prefill_start is not None:
                tr.add("serving.queue", int(req.arrival_time * 1e9),
                       int(req.prefill_start * 1e9),
                       prompt_tokens=len(req.prompt),
                       prefix_matched=req.prefix_matched)

    def on_prefill_chunk(self, req, t0_ns: Optional[int],
                         tokens: int, batched: bool = False) -> None:
        if t0_ns is None:
            return
        tr = req.trace
        if tr is not None:
            tr.add("serving.prefill_chunk", t0_ns, time.monotonic_ns(),
                   tokens=int(tokens), batched=bool(batched))

    def on_first_token(self, req) -> None:
        """Prefill -> running (all three admission-completion sites): SLO
        queue/TTFT observes + the admission span."""
        q = req.queue_seconds()
        if q is not None:
            _QUEUE_H.observe(q, tier=req.tier)
        t = req.ttft_seconds()
        if t is not None:
            _TTFT_H.observe(t, tier=req.tier)
            if self._on:
                self._ttft_acc.append(float(t))
        tr = req.trace
        if tr is not None and req.prefill_start is not None \
                and req.first_token_time is not None:
            tr.add("serving.admit", int(req.prefill_start * 1e9),
                   int(req.first_token_time * 1e9),
                   cached=req._cow_src is not None)

    def on_decode(self, t0_ns: Optional[int], running, k: int = 1,
                  kind: str = "decode", **args) -> None:
        """One decode / speculative-verify dispatch over the batch: one
        ring span, attached to every traced participant. The participants
        share ONE span dict by reference — this runs every engine tick for
        every running request, so per-request dict construction is exactly
        the overhead the <=3% budget forbids."""
        if t0_ns is None:
            return
        t1 = time.monotonic_ns()
        name = f"serving.{kind}"
        span_args = {"batch": len(running), "steps": int(k), **args}
        _spans.record_span(name, t0_ns, t1, cat="serving", args=span_args)
        shared = None
        for _, req in running:
            tr = req.trace
            if tr is not None:
                if shared is None:
                    shared = {"name": name, "begin_ns": int(t0_ns),
                              "end_ns": int(t1), "cat": "serving",
                              "tid": threading.get_ident() & 0xFFFF,
                              "args": span_args}
                tr.spans.append(shared)

    def on_rollback(self, req, rejected: int) -> None:
        tr = req.trace
        if tr is not None:
            now = time.monotonic_ns()
            tr.add("serving.rollback", now, now, rejected=int(rejected))

    def on_finish(self, req, reason: str) -> None:
        """Any terminal transition (stop/length/cancel/timeout/disconnect):
        SLO e2e + TPOT + goodput/shed accounting, the finish span, and the
        flight-arm request record."""
        tier = req.tier
        n = len(req.output_tokens)
        _GEN_TOKENS.inc(n)
        rate = req.decode_tokens_per_s()
        if rate is not None:
            _TOKRATE_H.observe(rate, tier=tier)
        if req.finish_time is not None:
            _E2E_H.observe(req.finish_time - req.arrival_time, tier=tier)
        if req.first_token_time is not None and req.finish_time is not None \
                and n > 1:
            _TPOT_H.observe((req.finish_time - req.first_token_time)
                            / (n - 1), tier=tier)
        if reason in _GOOD_REASONS:
            _GOODPUT_TOKENS.inc(n, tier=tier)
        else:
            _SHED.inc(tier=tier, reason=str(reason))
        tr = req.trace
        if tr is not None:
            now = time.monotonic_ns()
            tr.add("serving.finish", now, now, reason=str(reason),
                   output_tokens=n)
        if self._on or tr is not None:
            self._records.append(self._request_record(req))

    # -- per-tick sampling -------------------------------------------------
    def tick_begin(self) -> Optional[int]:
        """Start-of-tick: refresh the cached enable flags; returns the
        tick's start timestamp when anything records, else None."""
        self._on = metrics_enabled()
        self._trace_on = _spans.enabled()
        if self._on or self._trace_on:
            return time.monotonic_ns()
        return None

    def on_tick(self, t0_ns: Optional[int], out: Dict[str, Any]) -> None:
        """End-of-tick: tick span, then — every TICK_SAMPLE-th step —
        engine gauges, the tick snapshot record, and anomaly detection
        (+ flight dump). Between samples the hot path is one liveness
        timestamp and a decoded-token accumulate. Called under the engine
        lock."""
        eng = self.engine
        now = time.monotonic()
        self.last_tick_ts = now
        if t0_ns is not None and self._trace_on:
            _spans.record_span(
                "serving.tick", t0_ns, time.monotonic_ns(), cat="serving",
                args={"step": eng.steps, "decoded": out["decoded_tokens"],
                      "running": out["running"]})
        if not self._on:
            return
        self._decoded_acc += int(out["decoded_tokens"])
        n = self._tick_n
        self._tick_n = n + 1
        if n % self.TICK_SAMPLE:
            return
        running = int(out["running"])
        _SLOT_OCC.set(running / eng.max_slots if eng.max_slots else 0.0)
        _BATCH.set(running)
        self._tok_window.append((now, self._decoded_acc))
        rec: Dict[str, Any] = {
            "kind": "serving_tick", "step": eng.steps, "ts": time.time(),
            "decoded_tokens": self._decoded_acc,
            "running": running, "waiting": int(out["waiting"]),
            "kv_conservation_breach":
                0.0 if eng.allocator.conservation_ok() else 1.0,
        }
        self._decoded_acc = 0
        goodput = self._windowed_goodput()
        if goodput is not None:
            rec["goodput_tokens_per_s"] = goodput
            _GOODPUT_G.set(goodput)
        if self._admit_total:
            rate = self._admit_matched / self._admit_total
            rec["prefix_hit_rate"] = rate
            _HIT_RATE.set(rate)
        proposed = eng.spec_proposed
        if proposed:
            _SPEC_ACC.set(eng.spec_accepted / proposed)
        if self._ttft_acc:
            rec["ttft_s"] = sum(self._ttft_acc) / len(self._ttft_acc)
            self._ttft_acc = []
        self._ticks.append(rec)
        self.observe_record(rec)

    def observe_record(self, rec: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Feed one tick record through the serving anomaly detectors;
        dumps the flight arm on detection. Public seam (tests/servebench
        inject synthetic records through the same path on_tick uses)."""
        engine = self._anomaly_engine()
        if engine is None:
            return []
        events = engine.observe(rec)
        if events and self.dump:
            self._maybe_dump(events)
        return events

    def _windowed_goodput(self) -> Optional[float]:
        if len(self._tok_window) < 2:
            return None
        t_first = self._tok_window[0][0]
        t_last = self._tok_window[-1][0]
        dt = t_last - t_first
        if dt <= 0:
            return None
        # tokens of every tick after the window's first timestamp
        toks = sum(n for _, n in list(self._tok_window)[1:])
        return toks / dt

    def _anomaly_engine(self) -> Optional[_anomaly.AnomalyEngine]:
        """Lazy: detectors arm the first tick the flags allow it (dump
        handled here, so the shared engine runs with dump=False)."""
        if self._anomaly is None and serving_anomaly_on():
            self._anomaly = _anomaly.AnomalyEngine(
                _anomaly.serving_default_detectors(), dump=False)
        return self._anomaly

    def _maybe_dump(self, events: List[Dict[str, Any]]) -> None:
        step = self.engine.steps
        if step <= self._dump_armed_at:
            return
        self._dump_armed_at = step + self.dump_cooldown_steps
        sched = self.engine.sched
        inflight = [self._request_record(r)
                    for r in list(sched.prefilling)
                    + list(sched.running.values())]
        extra = {
            "anomaly": events[0],
            "serving_anomalies": events,
            "serving_requests": list(self._records) + inflight,
            "serving_ticks": list(self._ticks),
        }
        try:
            path = _flight.get_flight_recorder().dump(
                f"serving_{events[0]['kind']}", extra=extra)
            self.dumps.append(path)
        except OSError:
            pass

    def _request_record(self, req) -> Dict[str, Any]:
        rec = dict(req.telemetry())
        rec["ts"] = time.time()
        tr = req.trace
        if tr is not None:
            rec["trace"] = list(tr.spans)
        return rec

    # -- snapshots (HTTP handlers; takes the engine lock itself) -----------
    def recent_requests(self, n: int = 16) -> List[Dict[str, Any]]:
        with self.engine._lock:
            return list(self._records)[-int(n):]

    def recent_ticks(self, n: int = 16) -> List[Dict[str, Any]]:
        with self.engine._lock:
            return list(self._ticks)[-int(n):]

    def health_snapshot(self, loop_alive: bool = True,
                        stale_after_s: float = STALE_AFTER_S
                        ) -> Dict[str, Any]:
        """The serving /healthz body: one consistent engine snapshot taken
        under the engine lock (load-balancer semantics — 'ok' False means
        take this replica out of rotation; the body says why)."""
        now = time.monotonic()
        eng = self.engine
        with eng._lock:
            counts = eng.sched.counts()
            steps = eng.steps
            has_work = eng.sched.has_work()
            draining = bool(getattr(eng, "_draining", False))
            last_tick = self.last_tick_ts
            anomaly = self._anomaly
        out: Dict[str, Any] = {
            "status": "ok", "ok": True, "steps": steps,
            "last_tick_age_s": (round(now - last_tick, 3)
                                if last_tick is not None else None),
            **counts,
        }
        if not loop_alive:
            out["status"], out["ok"] = "dead", False
            return out
        recent = []
        if anomaly is not None:
            wall = time.time()
            recent = [a for a in anomaly.recent()
                      if wall - float(a.get("ts", 0)) <= ANOMALY_RECENT_S]
        out["anomalies_recent"] = len(recent)
        if recent:
            out["status"], out["ok"] = "anomalous", False
            out["last_anomaly"] = {k: v for k, v in recent[-1].items()
                                   if k in ("kind", "step", "value")}
        elif has_work and last_tick is not None \
                and now - last_tick > float(stale_after_s):
            out["status"], out["ok"] = "stale", False
        elif draining:
            # deliberate drain: not a fault, but ok=False so a load
            # balancer stops routing here while in-flight work finishes
            out["status"], out["ok"] = "draining", False
        elif steps == 0 and not has_work:
            out["status"] = "idle"
        return out
