"""ServingEngine: continuous-batching decode over the paged KV pool.

One engine tick (`step()`) = admit -> prefill chunk(s) -> one decode step:

  * decode is ONE compiled program over a fixed set of slots: every running
    sequence contributes its single last token; the paged ragged attention
    op reads each slot's own block table / length (idle slots point at the
    null block and are ignored). Page buffers are DONATED, so the pool is
    updated in place in HBM; sampling (greedy / per-slot temperature)
    happens inside the program.
  * prefill runs the model's existing contiguous cached path in a private
    workspace, one bounded chunk per tick per prompt (so long prompts
    interleave with decode instead of stalling it; a burst of short
    prompts may finish up to one prefill per IDLE slot in a tick), then
    scatters the finished prefix into the sequence's pages
    (paged.write_prefix) and joins the decode batch.
  * the int8 weight-only swap (quantization/weight_only.py) composes
    unchanged: quantized tables are buffers, and every compiled program
    here threads buffer values exactly like models/generation.py.

The decode loop is device-resident: block tables are the full worst-case
admission reservation uploaded once per request, the compiled step feeds
its own outputs (next tokens, advanced lengths, RNG seed) straight back
in, admission is one fused program (first-token argmax + slot scatter),
and sampled-token fetches are deferred and batched until a token's VALUE
can matter (eos check, length cap) — so a steady-state tick is a single
dispatch with no host round-trip.

Compiled-program keys are shape-stable: one decode program per engine, one
prefill/admit program per chunk bucket, one scatter per (workspace, block
count) — no per-request recompiles at steady state.
"""
from __future__ import annotations

import random
import threading
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import flags as _flags
from ..core.tensor import Tensor
from ..models.generation import init_kv_cache
from .blocks import BlockAllocator
from .observability import (
    _PREFILL_TOKENS,
    EngineStats,
    ServingObservability,
    new_engine_id,
)
from .paged import PagedKVPool, PagedLayerCache, write_prefix
from .scheduler import Request, Scheduler
from .speculative import NgramDrafter, SpecState

_flags.define_flag("serving_block_size", 16,
                   "KV-cache block size (tokens per page) for the serving "
                   "engine's paged pool.")
_flags.define_flag("serving_slots", 4,
                   "Decode batch slots: max sequences decoding concurrently.")
_flags.define_flag("serving_kv_blocks", 0,
                   "KV pool size in blocks. 0 = auto: enough for every slot "
                   "at max_model_len (no admission ever blocks on KV).")
_flags.define_flag("serving_prefill_chunk", 32,
                   "Prompt tokens prefilled per engine tick (must be a "
                   "multiple of serving_block_size); bounds how long a "
                   "prompt can stall the running decode batch.")
_flags.define_flag("serving_fuse_steps", 1,
                   "Greedy decode steps fused into one compiled dispatch. "
                   "1 (default) disables fusion: on CPU the fused loop's "
                   "carried KV pool costs more than the dispatches it "
                   "saves; worth >1 where dispatch latency dominates. "
                   "Sampled batches never fuse.")
_flags.define_flag("serving_max_model_len", 0,
                   "Serving context cap (prompt + generated). 0 = the "
                   "model's max_position_embeddings.")
_flags.define_flag("serving_prefix_cache", True,
                   "Automatic prefix caching: content-address full KV "
                   "blocks so prompts sharing a prefix skip its prefill "
                   "and share the blocks (copy-on-write on full-prompt "
                   "hits).")
_flags.define_flag("serving_spec_k", 0,
                   "Self-speculative decoding: max draft tokens verified "
                   "per tick. Drafts are n-gram / prompt-lookup matches "
                   "from the request's OWN token history; ONE multi-token "
                   "dispatch scores draft + bonus positions and the "
                   "longest matching prefix commits. 0 (default) disables "
                   "speculation. Greedy requests only (temperature > 0 "
                   "rows fall back to single-token decode in the same "
                   "batch); mutually exclusive with serving_fuse_steps > "
                   "1.")
_flags.define_flag("serving_spec_ngram", 3,
                   "Longest n-gram the self-speculation drafter matches "
                   "against the request's history (tries n down to 2).")
_flags.define_flag("serving_spec_pause", 32,
                   "Adaptive-k throttle: after 4 consecutive fruitless "
                   "speculation ticks a request pauses drafting for this "
                   "many engine ticks before probing again, so "
                   "non-repetitive traffic degrades to plain one-token "
                   "decode instead of paying verify windows that never "
                   "accept.")
_flags.define_flag("serving_max_queue", 0,
                   "Admission control: maximum requests waiting in the "
                   "scheduler queue. A submit() past this depth raises "
                   "QueueFullError (HTTP 503 + Retry-After at the server) "
                   "instead of growing the queue without bound. 0 = "
                   "unbounded (default).")
_flags.define_flag("serving_retry_after_s", 1.0,
                   "Base Retry-After hint (seconds) returned with 503 "
                   "queue-full responses.")
_flags.define_flag("serving_retry_after_jitter", 0.5,
                   "Fractional forward jitter on queue-full Retry-After "
                   "hints: each shed client is told to come back after "
                   "uniform[base, base * (1 + jitter)] seconds, so a burst "
                   "shed together does not retry in lockstep against a "
                   "recovering fleet. 0 disables jitter.")
_flags.define_flag("serving_prefill_bucket", 16,
                   "Length bucket (tokens) for the batched multi-prompt "
                   "prefill program: a burst's unmatched suffixes pad to "
                   "one bucketed [n_prompts, max_suffix] dispatch instead "
                   "of one program per prompt. 0 disables batching "
                   "(per-prompt chunked prefill only).")


class QueueFullError(RuntimeError):
    """submit() rejected: the scheduler queue is at FLAGS_serving_max_queue.
    Carries the depth/limit and a Retry-After hint so the HTTP layer can
    answer 503 with an honest backoff instead of a generic error."""

    def __init__(self, depth: int, limit: int,
                 retry_after_s: Optional[float] = None):
        self.depth = int(depth)
        self.limit = int(limit)
        if retry_after_s is None:
            base = float(_flags.get_flag("serving_retry_after_s"))
            jitter = max(0.0, float(
                _flags.get_flag("serving_retry_after_jitter")))
            # forward-only jitter: never tell a client to come back
            # EARLIER than the base hint, just spread the retry wave out
            retry_after_s = base * (1.0 + random.uniform(0.0, jitter))
        self.retry_after_s = float(retry_after_s)
        super().__init__(
            f"serving queue full: {self.depth} requests waiting >= "
            f"FLAGS_serving_max_queue={self.limit}; retry after "
            f"{self.retry_after_s:g}s")


class EngineDrainingError(RuntimeError):
    """submit() rejected: the engine is draining for a rolling restart.
    New work belongs on another replica; in-flight requests finish."""

    def __init__(self):
        super().__init__("serving engine is draining: not admitting new "
                         "requests (in-flight work will complete)")

# SLO histograms (TTFT/queue/TPOT/e2e/tokrate, tier-labeled) and the
# per-request lifecycle trace live in serving/observability.py; the engine
# reports transitions through self.obs. The per-tick speculation counters
# moved into SpecState.record (speculative.py).


class ServingEngine:
    """Continuous-batching serving runtime for a GenerationMixin causal LM
    (GPTForCausalLM / LlamaForCausalLM), int8-quantized or not.

    Quantize BEFORE constructing the engine: compiled programs capture the
    model's parameter/buffer lists at first use."""

    def __init__(self, model, *, max_slots: Optional[int] = None,
                 block_size: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 max_model_len: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 prefill_bucket: Optional[int] = None,
                 spec_k: Optional[int] = None,
                 spec_ngram: Optional[int] = None,
                 spec_pause: Optional[int] = None):
        self.model = model
        model.eval()
        n_layers, n_kv, head_dim, max_pos = model._decode_geometry()
        self.block_size = int(block_size or
                              _flags.get_flag("serving_block_size"))
        self.max_slots = int(max_slots or _flags.get_flag("serving_slots"))
        self.prefill_chunk = int(prefill_chunk or
                                 _flags.get_flag("serving_prefill_chunk"))
        flag_len = int(_flags.get_flag("serving_max_model_len"))
        self.max_model_len = int(max_model_len or flag_len or max_pos)
        self.max_model_len = min(self.max_model_len, int(max_pos))
        if self.prefill_chunk % self.block_size:
            raise ValueError("serving_prefill_chunk must be a multiple of "
                             "serving_block_size")
        self.max_blocks_per_seq = -(-self.max_model_len // self.block_size)
        auto_blocks = self.max_slots * self.max_blocks_per_seq + 1
        self.num_blocks = int(num_blocks or
                              _flags.get_flag("serving_kv_blocks") or
                              auto_blocks)
        self._dtype = model._cache_dtype()
        self._geometry = (n_layers, n_kv, head_dim)
        self.prefix_cache = (bool(_flags.get_flag("serving_prefix_cache"))
                             if prefix_cache is None else bool(prefix_cache))
        self.prefill_bucket = int(
            _flags.get_flag("serving_prefill_bucket")
            if prefill_bucket is None else prefill_bucket)
        self.pool = PagedKVPool(self.num_blocks, self.block_size, n_layers,
                                n_kv, head_dim, self._dtype)
        self.allocator = BlockAllocator(self.num_blocks, self.block_size,
                                        prefix_cache=self.prefix_cache)
        self.sched = Scheduler(self.allocator, self.max_slots,
                               self.max_model_len)
        # host mirror of per-slot decode state; the authoritative copies
        # live on device in _dev and are updated incrementally (per-slot
        # scatter on admission / block-table growth) — the decode loop
        # feeds its own outputs (next tokens, advanced seq_lens, RNG seed)
        # straight back in, and sampled-token fetches are DEFERRED and
        # batched (one transfer per flush) so host dispatch runs ahead of
        # device compute instead of syncing every tick
        self._tables = np.zeros((self.max_slots, self.max_blocks_per_seq),
                                np.int32)
        self._lens = np.zeros(self.max_slots, np.int32)
        self._toks = np.zeros(self.max_slots, np.int32)
        self._temps = np.zeros(self.max_slots, np.float32)
        # greedy decode steps fused per dispatch (1 = no fusion); sampled
        # batches always run unfused so every token sees a fresh seed tick
        self.fuse_steps = int(_flags.get_flag("serving_fuse_steps"))
        # self-speculative decoding (speculative.py): drafts verified in
        # one multi-token dispatch; 0 = off
        self.spec_k = int(_flags.get_flag("serving_spec_k")
                          if spec_k is None else spec_k)
        self.spec_ngram = int(_flags.get_flag("serving_spec_ngram")
                              if spec_ngram is None else spec_ngram)
        self.spec_pause = int(_flags.get_flag("serving_spec_pause")
                              if spec_pause is None else spec_pause)
        if self.spec_k > 0 and self.fuse_steps > 1:
            raise ValueError(
                "FLAGS_serving_fuse_steps > 1 and speculative decoding "
                "(serving_spec_k > 0) are mutually exclusive decode "
                "shapes: the fused loop carries a fixed one-token-per-"
                "step schedule that a variable-width verify window would "
                "miscompile. Disable one of them.")
        self._dev = None        # (toks, tables, lens, temps, seed) on device
        self._pending = []      # [(tokens_dev, [(idx, slot, req), ...])]
        self._jit = {}
        self._fns = None
        self._lock = threading.RLock()
        self._draining = False
        self._step_seed = 0
        self._sample_nonce = 0   # per-admission entropy for _sample_host
        self.steps = 0
        # prefill + speculation accounting now lives on the metrics
        # registry (serving_engine_events_total, labeled per engine
        # instance — see observability.EngineStats); the properties below
        # keep the historical int-attribute reads (servebench deltas,
        # tests) and stats() keeps its JSON shape
        self._stats = EngineStats(new_engine_id())
        # lifecycle hooks: request traces, SLO histograms, per-tick
        # gauges, serving anomaly detectors + flight arm
        self.obs = ServingObservability(self)

    # -- registry-backed counter views (historical int attributes) --------
    @property
    def prefill_programs(self) -> int:
        """Prefill dispatches, chunked + batched."""
        return self._stats["prefill_programs"]

    @property
    def batched_prefills(self) -> int:
        """Batched multi-prompt dispatches."""
        return self._stats["batched_prefills"]

    @property
    def prefill_tokens(self) -> int:
        """Prompt tokens actually computed (cache hits skip theirs)."""
        return self._stats["prefill_tokens"]

    @property
    def cow_admissions(self) -> int:
        """Full-prompt cache hits (zero prefill)."""
        return self._stats["cow_admissions"]

    @property
    def dedup_admissions(self) -> int:
        """Register-time block dedups applied."""
        return self._stats["dedup_admissions"]

    @property
    def spec_ticks(self) -> int:
        """Ticks that ran a verify window."""
        return self._stats["spec_ticks"]

    @property
    def spec_proposed(self) -> int:
        """Draft tokens offered."""
        return self._stats["spec_proposed"]

    @property
    def spec_accepted(self) -> int:
        """Draft tokens accepted."""
        return self._stats["spec_accepted"]

    @property
    def spec_rollbacks(self) -> int:
        """Ticks that rolled back >= 1 token."""
        return self._stats["spec_rollbacks"]

    # ------------------------------------------------------- compiled fns
    def _functional(self):
        """(paged_fn, static_fn, param_vals, buffer_vals) — built lazily so
        an int8 swap applied before first use is captured."""
        if self._fns is None:
            model = self.model
            static_fn, params, buffers = model._functional_forward()

            def paged_fn(pv, bv, ids, pages, bt, sl):
                saved_p = [(p._value, p.stop_gradient) for p in params]
                saved_b = [b._value for b in buffers]
                try:
                    for p, v in zip(params, pv):
                        p._value = v
                        p.stop_gradient = True
                    for b, v in zip(buffers, bv):
                        b._value = v
                    caches_t = [
                        PagedLayerCache(Tensor(k), Tensor(v), Tensor(bt),
                                        Tensor(sl))
                        for k, v in pages]
                    logits, ncs = model.forward(Tensor(ids), caches=caches_t,
                                                pos=None)
                    return logits._value, [(k._value, v._value)
                                           for k, v in ncs]
                finally:
                    for p, (v, sg) in zip(params, saved_p):
                        p._value, p.stop_gradient = v, sg
                    for b, v in zip(buffers, saved_b):
                        b._value = v

            self._fns = (paged_fn, static_fn, params, buffers)
        paged_fn, static_fn, params, buffers = self._fns
        return (paged_fn, static_fn,
                [p._value for p in params], [b._value for b in buffers])

    def _decode_jit(self, sampled: bool):
        """Two compiled variants: the all-greedy batch skips the threefry
        key derivation + Gumbel draw entirely (~0.2ms/step on CPU for a
        tiny model — a real fraction of the tick); temperature batches pay
        it. Both share the (tok, pages, bt, sl, temps, seed) signature so
        the engine can switch per tick as the batch mix changes."""
        key = ("decode", self.max_slots, self.max_blocks_per_seq, sampled)
        if key not in self._jit:
            paged_fn = self._functional()[0]

            def step(pv, bv, tok, pages, bt, sl, temps, seed):
                logits, new_pages = paged_fn(pv, bv, tok[:, None], pages,
                                             bt, sl)
                lg = logits[:, -1, :].astype(jnp.float32)
                greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                if sampled:
                    key_ = jax.random.fold_in(jax.random.PRNGKey(0), seed)
                    t = jnp.maximum(temps, 1e-6)[:, None]
                    draw = jax.random.categorical(
                        key_, lg / t, axis=-1).astype(jnp.int32)
                    nxt = jnp.where(temps > 0.0, draw, greedy)
                else:
                    nxt = greedy
                # sl/seed advance on device so steady-state ticks feed these
                # outputs straight back in (idle slots drift harmlessly —
                # they re-upload when the slot is next filled)
                return nxt, new_pages, sl + 1, seed + 1

            self._jit[key] = jax.jit(step, donate_argnums=(3, 5, 7))
        return self._jit[key]

    def _decode_multi_jit(self, k: int):
        """k decode steps fused into ONE compiled program (all-greedy
        batches only): per-dispatch host overhead — pytree flatten of ~30
        param leaves, pjit fast path, eager scatter bookkeeping — is a
        real fraction of a small model's step on CPU, and it amortizes
        k-fold. Returns the k sampled tokens flattened [k * slots] for the
        deferred-flush path plus the same carry as the 1-step program."""
        key = ("decode_multi", self.max_slots, self.max_blocks_per_seq, k)
        if key not in self._jit:
            paged_fn = self._functional()[0]

            def step(pv, bv, tok, pages, bt, sl, temps, seed):
                def body(i, carry):
                    tok, pages, sl, out = carry
                    logits, new_pages = paged_fn(pv, bv, tok[:, None],
                                                 pages, bt, sl)
                    lg = logits[:, -1, :].astype(jnp.float32)
                    nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                    return nxt, new_pages, sl + 1, out.at[i].set(nxt)

                out0 = jnp.zeros((k, tok.shape[0]), jnp.int32)
                tok, pages, sl, out = jax.lax.fori_loop(
                    0, k, body, (tok, pages, sl, out0))
                return tok, pages, sl, seed + k, out.reshape(-1)

            self._jit[key] = jax.jit(step, donate_argnums=(3, 5, 7))
        return self._jit[key]

    def _spec_jit(self, W: int, sampled: bool):
        """Speculative verify: score a W-token window (current token +
        W-1 drafts, zero-padded past each slot's own draft length) in ONE
        dispatch through the multi-query paged attention path, and accept
        the longest draft prefix that matches the greedy targets — all on
        device. Returns per-slot greedy targets [slots, W] (targets 0..acc
        are this tick's emitted tokens), the accepted count, the
        fed-back next token, and lengths advanced by acc+1 — an EXACT
        rollback of every rejected position, whose garbage KV stays
        masked behind the length in the slot's own private blocks.
        Sampled slots (temperature > 0) ride with a zero draft length:
        their column-0 logits are the same distribution the plain step
        would compute, and their next token is the categorical draw."""
        key = ("spec", self.max_slots, self.max_blocks_per_seq, W, sampled)
        if key not in self._jit:
            paged_fn = self._functional()[0]

            def step(pv, bv, win, pages, bt, sl, dls, temps, seed):
                logits, new_pages = paged_fn(pv, bv, win, pages, bt, sl)
                lg = logits.astype(jnp.float32)       # [slots, W, vocab]
                greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                # accepted = longest prefix where draft i+1 equals the
                # greedy target after window position i
                ok = ((win[:, 1:] == greedy[:, :-1])
                      & (jnp.arange(W - 1, dtype=jnp.int32)[None, :]
                         < dls[:, None]))
                acc = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1),
                              axis=1)
                nxt = jnp.take_along_axis(greedy, acc[:, None],
                                          axis=1)[:, 0]
                if sampled:
                    key_ = jax.random.fold_in(jax.random.PRNGKey(0), seed)
                    t = jnp.maximum(temps, 1e-6)[:, None]
                    draw = jax.random.categorical(
                        key_, lg[:, 0, :] / t, axis=-1).astype(jnp.int32)
                    nxt = jnp.where(temps > 0.0, draw, nxt)
                return greedy, acc, nxt, new_pages, sl + acc + 1, seed + 1

            self._jit[key] = jax.jit(step, donate_argnums=(3, 5, 8))
        return self._jit[key]

    def _clear_slot_jit(self):
        """Fused device-side slot clear for _finish: zero the slot's token,
        block-table row, length and temperature in ONE dispatch. The decode
        program keeps running over EVERY slot after a finish, so leaving
        the device copies stale would keep writing the dead sequence's K/V
        at advancing positions into its freed blocks — which the allocator
        may have already handed to a newly admitted request in a DIFFERENT
        slot (slot-LIFO and block-LIFO reuse can misalign). An all-zero
        table row points the idle slot at the null block, where its writes
        are harmless and its (len 0) context is never read."""
        key = ("clear_slot", self.max_slots, self.max_blocks_per_seq)
        if key not in self._jit:
            def clear(toks, bt, sl, temps, slot):
                return (toks.at[slot].set(0),
                        bt.at[slot].set(jnp.zeros((bt.shape[1],), bt.dtype)),
                        sl.at[slot].set(0),
                        temps.at[slot].set(0.0))

            self._jit[key] = jax.jit(clear)
        return self._jit[key]

    def _admit_jit(self, chunk):
        """Fused admission for greedy requests: the first token (argmax of
        the prefill logits, ON device — no host sync per admitted prompt)
        plus the slot's scatter into the live decode state, one dispatch.
        Eager per-field at[].set scatters cost ~0.5ms EACH on CPU; this is
        the difference between admission costing a tick and costing
        nothing. The slot index is traced, so one program serves every
        slot. No donation: the incoming token vector is also referenced by
        the deferred-flush queue."""
        key = ("admit", chunk, self.max_slots, self.max_blocks_per_seq)
        if key not in self._jit:
            def admit(logits, idx, toks, bt, sl, temps, slot, table, plen,
                      temp):
                lg = logits[0, idx].astype(jnp.float32)
                first = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                return (first[None],
                        toks.at[slot].set(first),
                        bt.at[slot].set(table),
                        sl.at[slot].set(plen),
                        temps.at[slot].set(temp))

            self._jit[key] = jax.jit(admit)
        return self._jit[key]

    def _prefill_jit(self, chunk, padded):
        key = ("prefill", chunk, padded)
        if key not in self._jit:
            static_fn = self._functional()[1]

            def pf(pv, bv, ids, caches, pos):
                return static_fn(pv, bv, ids, caches, pos)

            self._jit[key] = jax.jit(pf, donate_argnums=(3,))
        return self._jit[key]

    def _gather_jit(self, padded, mb):
        """Materialize a prefill workspace whose head is a cached prefix
        gathered from the pool pages (prefix-cache partial hit: the suffix
        chunks run the contiguous cached path on top of it). Pages are NOT
        donated — they stay the live pool."""
        key = ("gather", padded, mb)
        if key not in self._jit:
            bs = self.block_size
            n = mb * bs

            def g(pages, table):
                out = []
                for kp, vp in pages:
                    hkv, d = kp.shape[2], kp.shape[3]
                    k = jnp.zeros((1, padded, hkv, d), kp.dtype)
                    v = jnp.zeros((1, padded, hkv, d), vp.dtype)
                    k = k.at[0, :n].set(kp[table].reshape(n, hkv, d))
                    v = v.at[0, :n].set(vp[table].reshape(n, hkv, d))
                    out.append((k, v))
                return out

            self._jit[key] = jax.jit(g)
        return self._jit[key]

    def _admit_cow_jit(self):
        """Full-prompt cache hit: fork the last shared block (device copy
        src -> dst across every layer — the only block the re-decoded last
        prompt token will write) and scatter the slot's decode state, one
        dispatch. Pages are donated (in-place pool update); the decode
        state tensors are not (the token vector may be referenced by the
        deferred-flush queue)."""
        key = ("admit_cow", self.max_slots, self.max_blocks_per_seq)
        if key not in self._jit:
            def f(pages, toks, bt, sl, temps, src, dst, slot, table, plen,
                  tok, temp):
                new = [(kp.at[dst].set(kp[src]), vp.at[dst].set(vp[src]))
                       for kp, vp in pages]
                return (new,
                        toks.at[slot].set(tok),
                        bt.at[slot].set(table),
                        sl.at[slot].set(plen),
                        temps.at[slot].set(temp))

            self._jit[key] = jax.jit(f, donate_argnums=(0,))
        return self._jit[key]

    def _batched_prefill_jit(self, S, P):
        """ONE compiled program admitting up to max_slots prompts: gather
        each row's cached prefix into a contiguous [n, P] workspace, run
        the model over the padded [n, S] suffixes with PER-ROW position
        offsets, argmax each row's first token at its own last real index,
        scatter the workspaces back to the pool pages and the rows' decode
        state into the live slots — so a burst of N admissions costs one
        dispatch instead of N.

        Padding rows are inert by construction: their block tables are all
        null (write-back garbage lands in block 0, the idle-slot dumping
        ground) and their slot index is max_slots, which jax's scatter
        drops as out-of-bounds. Shared prefix blocks appear in several
        rows' tables; every row scatters back the IDENTICAL bytes it
        gathered, so duplicate-index writes are deterministic."""
        n = self.max_slots
        key = ("batched_prefill", n, S, P)
        if key not in self._jit:
            static_fn = self._functional()[1]
            bs = self.block_size
            nb = P // bs

            def bp(pv, bv, pages, ids, pos, tP, last, slots, bt_rows,
                   plens, temps, d_toks, d_bt, d_sl, d_temps):
                caches = []
                for kp, vp in pages:
                    hkv, d = kp.shape[2], kp.shape[3]
                    caches.append((kp[tP].reshape(n, P, hkv, d),
                                   vp[tP].reshape(n, P, hkv, d)))
                logits, ncs = static_fn(pv, bv, ids, caches, pos)
                lg = logits[jnp.arange(n), last].astype(jnp.float32)
                first = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                flat = tP.reshape(-1)
                new_pages = []
                for (kp, vp), (k, v) in zip(pages, ncs):
                    hkv, d = kp.shape[2], kp.shape[3]
                    new_pages.append(
                        (kp.at[flat].set(k.reshape(n * nb, bs, hkv, d)),
                         vp.at[flat].set(v.reshape(n * nb, bs, hkv, d))))
                return (first, new_pages,
                        d_toks.at[slots].set(first),
                        d_bt.at[slots].set(bt_rows),
                        d_sl.at[slots].set(plens),
                        d_temps.at[slots].set(temps))

            self._jit[key] = jax.jit(bp, donate_argnums=(2,))
        return self._jit[key]

    def _scatter_jit(self, padded, nb):
        """Scatter a prefilled workspace prefix into the pool pages. The
        workspace slicing happens INSIDE the program (an eager slice per
        layer per prompt is pure dispatch overhead); both the pool and the
        spent workspace are donated."""
        key = ("scatter", padded, nb)
        if key not in self._jit:
            bs = self.block_size
            n = nb * bs

            def sc(pages, caches, table):
                return [write_prefix(kp, vp, k[0, :n], v[0, :n], table,
                                     block_size=bs)
                        for (kp, vp), (k, v) in zip(pages, caches)]

            self._jit[key] = jax.jit(sc, donate_argnums=(0,))
        return self._jit[key]

    # ------------------------------------------------------------- intake
    def submit(self, prompt: List[int], max_new_tokens: int = 16,
               temperature: float = 0.0,
               eos_token_id: Optional[int] = None,
               request_id: Optional[str] = None,
               tier: str = "default",
               trace_ctx: Optional[dict] = None,
               prefill_only: bool = False) -> Request:
        req = Request(prompt, max_new_tokens=max_new_tokens,
                      temperature=temperature, eos_token_id=eos_token_id,
                      request_id=request_id, tier=tier, trace_ctx=trace_ctx,
                      prefill_only=prefill_only)
        max_queue = int(_flags.get_flag("serving_max_queue"))
        with self._lock:
            if self._draining:
                self.obs.on_shed(req, "draining")
                raise EngineDrainingError()
            depth = len(self.sched.waiting)
            if max_queue > 0 and depth >= max_queue:
                self.obs.on_shed(req, "queue_full")
                raise QueueFullError(depth, max_queue)
            self.obs.on_submit(req)
            self.sched.submit(req)
        return req

    # ----------------------------------------------------------- drain
    def drain(self):
        """Graceful drain for rolling restarts: stop admitting new
        requests (submit() raises EngineDrainingError) while everything
        already accepted — queued, prefilling, running — completes
        normally. /healthz reports `draining` with ok=False so a load
        balancer takes the replica out of rotation."""
        with self._lock:
            self._draining = True

    def resume(self):
        """Re-open admissions after a drain()."""
        with self._lock:
            self._draining = False

    @property
    def draining(self) -> bool:
        return self._draining

    def drained(self) -> bool:
        """True once a draining engine has no in-flight work left."""
        with self._lock:
            return self._draining and not self.sched.has_work()

    def cancel(self, req: Request, reason: str = "cancelled") -> bool:
        """Evict a request in any pre-finished state — queued, prefilling,
        or running — releasing its slot and worst-case KV reservation
        immediately. Used by the HTTP front end when a client times out or
        disconnects, so abandoned requests stop consuming serving capacity.
        Returns False if the request had already finished."""
        with self._lock:
            if req.state == "finished":
                return False
            self._finish(req, reason)
            return True

    # ------------------------------------------- KV-block streaming wire
    def export_kv_blocks(self, tokens: List[int]) -> List[dict]:
        """Serialize the RESIDENT full-block prefix of `tokens` for
        streaming to another replica: one record per indexed block, chain
        order, each carrying the chain digest (hex), the previous link's
        digest, the block's token ids, and the raw per-layer (K, V) page
        bytes gathered from the device pool. Read-only; the wire format is
        what ingest_kv_blocks() (and the HTTP /kv/ingest endpoint, after
        base64) accepts."""
        with self._lock:
            recs = self.allocator.export_prefix(tokens)
            if not recs:
                return []
            blks = np.asarray([r["block"] for r in recs], np.int32)
            layers = [(np.asarray(jax.device_get(kp[blks])),
                       np.asarray(jax.device_get(vp[blks])))
                      for kp, vp in self.pool.layers]
            out = []
            for i, r in enumerate(recs):
                out.append({
                    "digest": r["digest"].hex(),
                    "prev": r["prev"].hex(),
                    "tokens": r["tokens"],
                    "layers": [(k[i].tobytes(), v[i].tobytes())
                               for k, v in layers],
                })
            return out

    def ingest_kv_blocks(self, records: List[dict]) -> dict:
        """Admit streamed KV blocks into the local pool as prefix-cache
        entries. Each record is verified against the chain hash
        (allocator.import_block) and its byte payload against the pool
        geometry BEFORE anything is claimed; a failed link stops the chain
        (descendants could never be matched past the hole). Idempotent:
        already-resident digests are deduped without touching the pool.
        Returns {"imported", "dedup", "rejected", "skipped", "bytes"}."""
        n_layers = len(self.pool.layers)
        kp0 = self.pool.layers[0][0]
        np_dtype = np.dtype(kp0.dtype)
        blk_shape = (self.block_size, kp0.shape[2], kp0.shape[3])
        blk_bytes = int(np.prod(blk_shape)) * np_dtype.itemsize
        imported = dedup = rejected = skipped = nbytes = 0
        with self._lock:
            prev = b""
            pend = []               # (block_id, [(k_arr, v_arr), ...])
            for i, rec in enumerate(records):
                try:
                    digest = bytes.fromhex(rec["digest"])
                    rec_prev = bytes.fromhex(rec["prev"])
                    layers = rec["layers"]
                    if rec_prev != prev:
                        raise ValueError("broken chain: prev digest does "
                                         "not match the previous record")
                    if len(layers) != n_layers or any(
                            len(k) != blk_bytes or len(v) != blk_bytes
                            for k, v in layers):
                        raise ValueError("payload does not match the pool "
                                         "geometry")
                    blk, fresh = self.allocator.import_block(
                        prev, rec["tokens"], digest)
                except ValueError:
                    # corrupt/mislabeled link: everything after it hangs
                    # off an unverifiable digest — drop the rest
                    rejected += 1
                    skipped += len(records) - i - 1
                    break
                except MemoryError:
                    # pool full: a mid-chain hole makes descendants
                    # unmatchable, so don't import past it either
                    skipped += len(records) - i
                    break
                prev = digest
                if fresh:
                    imported += 1
                    nbytes += 2 * n_layers * blk_bytes
                    pend.append((blk, [
                        (np.frombuffer(k, np_dtype).reshape(blk_shape),
                         np.frombuffer(v, np_dtype).reshape(blk_shape))
                        for k, v in layers]))
                else:
                    dedup += 1
            if pend:
                idx = jnp.asarray(np.asarray([b for b, _ in pend],
                                             np.int32))
                new_layers = []
                for li, (kp, vp) in enumerate(self.pool.layers):
                    k_new = jnp.asarray(np.stack([a[li][0]
                                                  for _, a in pend]))
                    v_new = jnp.asarray(np.stack([a[li][1]
                                                  for _, a in pend]))
                    new_layers.append((kp.at[idx].set(k_new),
                                       vp.at[idx].set(v_new)))
                self.pool.replace(new_layers)
        return {"imported": imported, "dedup": dedup, "rejected": rejected,
                "skipped": skipped, "bytes": nbytes}

    # ------------------------------------------------------------ tick
    def step(self) -> dict:
        """One engine tick: admissions, one prefill chunk, one decode step
        over the running batch. Returns per-tick stats."""
        with self._lock:
            t0 = self.obs.tick_begin()
            admitted = self.sched.admit()
            for req in admitted:
                self.obs.on_admitted(req)
            # full-prompt cache hits never prefill: copy-on-write the last
            # shared block and drop straight into the decode batch
            for req in [r for r in self.sched.prefilling
                        if r._cow_src is not None]:
                self._admit_cached(req)
            # batched multi-prompt prefill: a burst of short unmatched
            # suffixes admits in ONE dispatch instead of one per prompt
            if self.prefill_bucket > 0:
                batch = [r for r in self.sched.prefilling
                         if r._ws_caches is None and r.temperature <= 0.0
                         and 0 < (len(r.prompt) - r.prefill_pos)
                         <= self.prefill_chunk]
                if len(batch) >= 2:
                    self._batched_prefill(batch[:self.max_slots])
            # one prefill chunk per tick bounds how long a prompt can stall
            # the running batch — but a slot with NOTHING to decode isn't
            # stalled, so after a burst (many admissions, few running) keep
            # prefilling up to one chunk per idle slot and the whole wave
            # joins decode this tick instead of trickling in serially
            budget = max(1, self.max_slots - len(self.sched.running))
            for _ in range(budget):
                req = self.sched.next_prefill()
                if req is None:
                    break
                self._prefill_one_chunk(req)
                if self.sched.next_prefill() is req:
                    break   # long prompt mid-prefill: one chunk per tick
            decoded = self._decode_step() if self.sched.running else 0
            self.steps += 1
            out = {"admitted": len(admitted), "decoded_tokens": decoded,
                   **self.sched.counts()}
            self.obs.on_tick(t0, out)
            return out

    def run_until_idle(self, max_steps: int = 1_000_000) -> int:
        steps = 0
        while self.sched.has_work():
            self.step()
            steps += 1
            if steps >= max_steps:
                raise RuntimeError("serving engine did not drain "
                                   f"within {max_steps} steps")
        return steps

    def generate(self, prompts, max_new_tokens: int = 16,
                 temperature: float = 0.0,
                 eos_token_id: Optional[int] = None):
        """Blocking convenience (tests): submit all, drain, return the full
        sequences (prompt + generated) as lists of ints."""
        reqs = [self.submit(list(p), max_new_tokens=max_new_tokens,
                            temperature=temperature,
                            eos_token_id=eos_token_id) for p in prompts]
        self.run_until_idle()
        return [r.prompt + r.output_tokens for r in reqs]

    # ----------------------------------------------------------- prefill
    def _admit_cached(self, req: Request) -> None:
        """Full-prompt prefix-cache hit: every prompt block is already in
        the pool, so the request enters decode DIRECTLY — zero prefill
        dispatches. The decode program recomputes the last prompt token's
        step (token = prompt[-1] at seq_len = plen - 1): its K/V write
        lands in the copy-on-write fork of the final shared block, and its
        logits yield the first generated token on the next decode tick."""
        if req.prefill_only:
            # every prompt block is already resident and indexed: a
            # prefill-only pass has nothing to compute OR publish — finish
            # without the COW dispatch (the fork block frees with the
            # reservation)
            self._finish(req, "prefill_complete")
            return
        plen = len(req.prompt)
        slot = req.slot
        table = np.asarray(self.allocator.table(req.request_id), np.int32)
        dst = int(table[plen // self.block_size - 1])
        src = int(req._cow_src)
        self._tables[slot] = 0
        self._tables[slot, :len(table)] = table
        self._lens[slot] = plen - 1
        self._toks[slot] = req.prompt[-1]
        self._temps[slot] = req.temperature
        if self._dev is None:
            self._dev_init()
        d_toks, d_tables, d_lens, d_temps, d_seed = self._dev
        new_layers, n_toks, n_bt, n_sl, n_temps = self._admit_cow_jit()(
            self.pool.layers, d_toks, d_tables, d_lens, d_temps,
            src, dst, slot, self._tables[slot], plen - 1,
            int(req.prompt[-1]), req.temperature)
        self.pool.replace(new_layers)
        self._dev = (n_toks, n_bt, n_sl, n_temps, d_seed)
        self._stats.inc("cow_admissions")
        self.sched.start_running(req)
        self.obs.on_first_token(req)

    def _batched_prefill(self, reqs: List[Request]) -> None:
        """Admit a burst of prompts in ONE dispatch (see
        _batched_prefill_jit). Rows are the burst's unmatched suffixes,
        padded to a bucketed [n, S]; the workspace holds each row's full
        context (cached prefix + suffix) padded to P tokens. Greedy-only:
        each row's first token is argmaxed on device and its fetch
        deferred like any decode token."""
        t0 = self.obs.now()
        _, _, pv, bv = self._functional()
        n = self.max_slots
        bs = self.block_size
        bucket = max(self.prefill_bucket, 1)
        suffixes = [len(r.prompt) - r.prefill_pos for r in reqs]
        S = -(-max(suffixes) // bucket) * bucket
        ctx = max(r.prefill_pos + S for r in reqs)
        # quantize the workspace length to the CHUNK grid, not the bucket
        # grid: P drives the compiled shape, and a fine grid means a fresh
        # XLA compile per burst composition (prefill_pos varies with cache
        # hits) — a compile storm costs far more than the extra padding
        P = -(-ctx // self.prefill_chunk) * self.prefill_chunk
        nb = P // bs
        ids = np.zeros((n, S), np.int32)
        pos = np.zeros(n, np.int32)
        tP = np.zeros((n, nb), np.int32)
        last = np.zeros(n, np.int32)
        slots = np.full(n, self.max_slots, np.int32)   # OOB -> dropped
        bt_rows = np.zeros((n, self.max_blocks_per_seq), np.int32)
        plens = np.zeros(n, np.int32)
        temps = np.zeros(n, np.float32)
        for r, req in enumerate(reqs):
            plen = len(req.prompt)
            start = req.prefill_pos
            take = plen - start
            ids[r, :take] = req.prompt[start:]
            pos[r] = start
            table = self.allocator.table(req.request_id)
            tP[r, :min(nb, len(table))] = table[:nb]
            last[r] = take - 1
            slots[r] = req.slot
            bt_rows[r, :len(table)] = table
            plens[r] = plen
            temps[r] = req.temperature
        if self._dev is None:
            self._dev_init()
        d_toks, d_tables, d_lens, d_temps, d_seed = self._dev
        first_dev, new_layers, n_toks, n_bt, n_sl, n_temps = \
            self._batched_prefill_jit(S, P)(
                pv, bv, self.pool.layers, jnp.asarray(ids),
                jnp.asarray(pos), jnp.asarray(tP), jnp.asarray(last),
                jnp.asarray(slots), jnp.asarray(bt_rows),
                jnp.asarray(plens), jnp.asarray(temps),
                d_toks, d_tables, d_lens, d_temps)
        self.pool.replace(new_layers)
        self._dev = (n_toks, n_bt, n_sl, n_temps, d_seed)
        self._stats.inc("batched_prefills")
        self._stats.inc("prefill_programs")
        computed = sum(suffixes)
        self._stats.inc("prefill_tokens", computed)
        _PREFILL_TOKENS.inc(computed)
        self._pending.append(
            (first_dev, [(r, req.slot, req) for r, req in enumerate(reqs)]))
        flush = False
        for r, req in enumerate(reqs):
            slot = req.slot
            self._tables[slot] = bt_rows[r]
            self._lens[slot] = plens[r]
            self._toks[slot] = 0          # fetched at the next flush
            self._temps[slot] = req.temperature
            req.prefill_pos = len(req.prompt)
            req._pending_n += 1
            if self.prefix_cache:
                self.allocator.register_prefix(req.request_id, req.prompt)
                if self.allocator.last_dedup:
                    # live dedup: identical blocks prefilled concurrently
                    # in this burst now share storage — adopt the swapped
                    # table on host AND in the already-uploaded device row
                    table = np.asarray(
                        self.allocator.table(req.request_id), np.int32)
                    self._tables[slot] = 0
                    self._tables[slot, :len(table)] = table
                    d_toks, d_tables, d_lens, d_temps, d_seed = self._dev
                    self._dev = (
                        d_toks,
                        d_tables.at[slot].set(
                            jnp.asarray(self._tables[slot])),
                        d_lens, d_temps, d_seed)
                    self._stats.inc("dedup_admissions")
            self.obs.on_prefill_chunk(req, t0, suffixes[r], batched=True)
            if req.prefill_only:
                # the row rode the shared dispatch for its KV only; finish
                # instead of joining decode (the deferred first-token fetch
                # skips finished requests at flush)
                self._finish(req, "prefill_complete")
                continue
            self.sched.start_running(req)
            self.obs.on_first_token(req)
            if req.eos_token_id is not None or req.max_new_tokens <= 1:
                flush = True
        if flush:
            self._flush_pending()

    def _prefill_one_chunk(self, req: Request) -> None:
        t0 = self.obs.now()
        _, _, pv, bv = self._functional()
        n_layers, n_kv, head_dim = self._geometry
        plen = len(req.prompt)
        chunk = self.prefill_chunk
        # chunk writes start at prefix_matched (a block multiple, not
        # necessarily a chunk multiple): the workspace must cover the LAST
        # chunk window, or dynamic_update_slice would clamp it backwards
        padded = (req.prefix_matched
                  + -(-(plen - req.prefix_matched) // chunk) * chunk)
        if req._ws_caches is None:
            if req.prefix_matched:
                # partial prefix hit: seed the workspace with the cached
                # blocks so the suffix chunks run on top of real context
                mb = req.prefix_matched // self.block_size
                head = np.asarray(
                    self.allocator.table(req.request_id)[:mb], np.int32)
                req._ws_caches = self._gather_jit(padded, mb)(
                    self.pool.layers, head)
            else:
                req._ws_caches = init_kv_cache(1, padded, n_layers, n_kv,
                                               head_dim, self._dtype)
        start = req.prefill_pos
        ids = np.zeros((1, chunk), np.int32)
        take = min(chunk, plen - start)
        ids[0, :take] = req.prompt[start:start + take]
        logits, req._ws_caches = self._prefill_jit(chunk, padded)(
            pv, bv, jnp.asarray(ids), req._ws_caches,
            jnp.asarray(start, jnp.int32))
        req.prefill_pos = start + take
        self._stats.inc("prefill_programs")
        self._stats.inc("prefill_tokens", take)
        _PREFILL_TOKENS.inc(take)
        self.obs.on_prefill_chunk(req, t0, take)
        if req.prefill_pos < plen:
            return
        # prompt fully prefilled: sample the first token from the last REAL
        # position of this chunk, scatter the prefix into pages, join
        # decode. The table is the WHOLE worst-case reservation (scheduler
        # admit); only the prompt-covering prefix is scattered — decode
        # appends fill the rest position by position.
        table = np.asarray(self.allocator.table(req.request_id), np.int32)
        nb = -(-plen // self.block_size)
        new_layers = self._scatter_jit(padded, nb)(
            self.pool.layers, req._ws_caches, table[:nb])
        self.pool.replace(new_layers)
        req._ws_caches = None
        if self.prefix_cache:
            # the prompt's full blocks are now resident in the pool: index
            # them so later prompts sharing the prefix skip its prefill
            self.allocator.register_prefix(req.request_id, req.prompt)
            if self.allocator.last_dedup:
                # live dedup (a twin registered first while this prompt
                # prefilled): adopt the swapped table before the slot's
                # device row is uploaded below
                table = np.asarray(self.allocator.table(req.request_id),
                                   np.int32)
                self._stats.inc("dedup_admissions")
        if req.prefill_only:
            # disaggregated prefill pass: the prompt's KV is scattered and
            # its full blocks indexed — they stay resident (evictable,
            # matchable, exportable) after the finish releases the
            # sequence. No first token: the decode replica samples it.
            self._finish(req, "prefill_complete")
            return
        slot = req.slot
        self._tables[slot] = 0
        self._tables[slot, :len(table)] = table
        self._lens[slot] = plen
        self._temps[slot] = req.temperature
        # a greedy no-eos request never needs its first token's VALUE on
        # the host this tick — sample it on device and defer the fetch, so
        # admission doesn't block the pipeline on prefill compute
        defer = (req.temperature <= 0.0 and req.eos_token_id is None
                 and req.max_new_tokens > 1)
        if defer:
            if self._dev is None:
                self._dev_init()
            d_toks, d_tables, d_lens, d_temps, d_seed = self._dev
            first_dev, n_toks, n_bt, n_sl, n_temps = self._admit_jit(chunk)(
                logits, plen - 1 - start, d_toks, d_tables, d_lens, d_temps,
                slot, self._tables[slot], plen, req.temperature)
            self._dev = (n_toks, n_bt, n_sl, n_temps, d_seed)
            self._pending.append((first_dev, [(0, slot, req)]))
            req._pending_n += 1
        else:
            first = self._sample_host(
                np.asarray(jax.device_get(logits[0, plen - 1 - start])), req)
            self._toks[slot] = first
            if self._dev is not None:
                # join the live decode batch by scattering this slot's
                # state into the device copies (host-known scalars — no
                # sync, the other slots' in-flight tokens are untouched)
                d_toks, d_tables, d_lens, d_temps, d_seed = self._dev
                self._dev = (d_toks.at[slot].set(first),
                             d_tables.at[slot].set(
                                 jnp.asarray(self._tables[slot])),
                             d_lens.at[slot].set(plen),
                             d_temps.at[slot].set(req.temperature),
                             d_seed)
            req.output_tokens.append(first)
            req._progress.set()
        self.sched.start_running(req)
        self.obs.on_first_token(req)
        if not defer:
            if req.eos_token_id is not None and first == req.eos_token_id:
                self._finish(req, "stop")
            elif len(req.output_tokens) >= req.max_new_tokens:
                self._finish(req, "length")

    def _sample_host(self, logits: np.ndarray, req: Request) -> int:
        """First-token sampling for non-deferred admissions: same
        fold_in(PRNGKey(0), seed) threefry scheme as the compiled decode
        step, plus a per-admission nonce — two sampled requests admitted in
        the SAME tick must draw from distinct streams, and the first token
        must not replay what a decode tick at the same seed would emit."""
        if req.temperature <= 0.0:
            return int(logits.argmax())
        self._sample_nonce += 1
        key = jax.random.fold_in(jax.random.PRNGKey(0), self._step_seed)
        key = jax.random.fold_in(key, self._sample_nonce)
        lg = jnp.asarray(logits, jnp.float32) / max(req.temperature, 1e-6)
        return int(jax.random.categorical(key, lg, axis=-1))

    # ------------------------------------------------------------ decode
    def _dev_init(self):
        self._dev = (jnp.asarray(self._toks), jnp.asarray(self._tables),
                     jnp.asarray(self._lens), jnp.asarray(self._temps),
                     jnp.asarray(self._step_seed, jnp.int32))

    def _decode_step(self) -> int:
        if self.spec_k > 0:
            decoded = self._spec_step()
            if decoded is not None:
                return decoded
        t0 = self.obs.now()
        _, _, pv, bv = self._functional()
        running = list(self.sched.running.items())
        if self._dev is None:
            self._dev_init()
        d_toks, d_tables, d_lens, d_temps, d_seed = self._dev
        # block tables are the full worst-case reservation, uploaded once
        # at admission — a steady-state decode tick touches NO host state
        # but the pending counters: no allocator call, no table scatter,
        # just one compiled-program dispatch
        needs_sampling = any(req.temperature > 0.0 for _, req in running)
        # fuse 4 decode steps into one dispatch for all-greedy batches. A
        # slot whose budget runs out mid-chunk just overshoots: the extra
        # tokens are dropped at flush (eos overshoot was already truncated
        # there), and the overflow KV writes can only land in the null
        # block or the finishing slot's own about-to-be-freed pages —
        # never another sequence's. Prefill still gets its chunk every
        # dispatch, so fusing costs admission at most 3 steps of latency
        # per queued prompt.
        k = 1 if needs_sampling else self.fuse_steps
        if k == 1:
            nxt, new_layers, new_lens, new_seed = self._decode_jit(
                needs_sampling)(
                pv, bv, d_toks, self.pool.layers, d_tables, d_lens, d_temps,
                d_seed)
            toks = nxt
            items = [(slot, slot, req) for slot, req in running]
        else:
            nxt, new_layers, new_lens, new_seed, toks = \
                self._decode_multi_jit(k)(
                    pv, bv, d_toks, self.pool.layers, d_tables, d_lens,
                    d_temps, d_seed)
            items = [(i * self.max_slots + slot, slot, req)
                     for i in range(k) for slot, req in running]
        self.pool.replace(new_layers)
        self._dev = (nxt, d_tables, new_lens, d_temps, new_seed)
        self._step_seed += k
        # defer the token fetch: host bookkeeping below only needs COUNTS.
        # Flush (one batched transfer) when a token value can matter — a
        # request with an eos_token_id (checked every token), or one whose
        # count reached its length cap this tick.
        self._pending.append((toks, items))
        self.obs.on_decode(t0, running, k)
        flush = False
        for slot, req in running:
            req._pending_n += k
            self._lens[slot] += k
            if (req.eos_token_id is not None
                    or len(req.output_tokens) + req._pending_n
                    >= req.max_new_tokens
                    or int(self._lens[slot]) >= self.max_model_len):
                flush = True
        if flush:
            self._flush_pending()
        return len(running) * k

    def _spec_step(self) -> Optional[int]:
        """One speculative tick, or None to fall through to the plain
        deferred-fetch decode path (no request may draft right now — all
        paused by the adaptive throttle, sampled, or out of budget).

        Speculation is inherently synchronous on the host side: drafting
        needs every emitted token's VALUE, so the tick flushes the
        deferred queue first and fetches its own (targets, accepted)
        results eagerly. The adaptive pause keeps that cost off
        non-repetitive traffic — when nothing drafts, the plain
        pipelined path runs untouched."""
        # cheap pre-check before paying the flush: is anyone allowed to
        # draft this tick? (draft_k needs no token values)
        active = False
        for slot, req in self.sched.running.items():
            if req.temperature > 0.0:
                continue
            if req._spec is None:
                req._drafter = NgramDrafter(max_n=self.spec_ngram)
                req._spec = SpecState(self.spec_k,
                                      pause_ticks=self.spec_pause)
            if req._spec.draft_k(self.steps) > 0:
                active = True
        if not active:
            return None
        self._flush_pending()
        running = list(self.sched.running.items())
        if not running:
            return 0
        # draft per slot, capped so a fully-accepted window can never
        # overrun the token budget, the context cap, or the worst-case
        # block reservation (rollback never needs to grow a table)
        drafts = {}
        for slot, req in running:
            if req.temperature > 0.0 or req._spec is None:
                continue
            rid = req.request_id
            room = (self.block_size * len(self.allocator.table(rid))
                    - self.allocator.seq_len(rid) - 1)
            k_r = min(req._spec.draft_k(self.steps),
                      req.max_new_tokens - len(req.output_tokens) - 1,
                      self.max_model_len - 1 - int(self._lens[slot]),
                      room)
            if k_r <= 0:
                continue
            d = req._drafter.propose(req.prompt + req.output_tokens, k_r)
            drafts[slot] = d
            if not d:
                req._spec.record(0, 0, self.steps)
        if not any(drafts.values()):
            return None     # nobody produced a draft: plain path
        # FIXED window width: the verify program is compiled once for
        # W = spec_k + 1 and shorter (or absent) drafts are masked by
        # dls — a varying per-tick max draft length would recompile the
        # step every time the adaptive throttle moved k
        W = 1 + self.spec_k
        _, _, pv, bv = self._functional()
        if self._dev is None:
            self._dev_init()
        d_toks, d_tables, d_lens, d_temps, d_seed = self._dev
        win = np.zeros((self.max_slots, W), np.int32)
        dls = np.zeros(self.max_slots, np.int32)
        for slot, req in running:
            win[slot, 0] = self._toks[slot]
            d = drafts.get(slot, ())
            win[slot, 1:1 + len(d)] = d
            dls[slot] = len(d)
        needs_sampling = any(req.temperature > 0.0 for _, req in running)
        t0 = self.obs.now()
        greedy, acc, nxt, new_layers, new_sl, new_seed = self._spec_jit(
            W, needs_sampling)(
            pv, bv, jnp.asarray(win), self.pool.layers, d_tables, d_lens,
            jnp.asarray(dls), d_temps, d_seed)
        self.pool.replace(new_layers)
        self._dev = (nxt, d_tables, new_sl, d_temps, new_seed)
        self._step_seed += 1
        self._stats.inc("spec_ticks")
        self.obs.on_decode(t0, running, 1, kind="spec_verify",
                           window=W)
        greedy_h, acc_h, nxt_h = jax.device_get((greedy, acc, nxt))
        decoded = 0
        touched = []
        for slot, req in running:
            dl = int(dls[slot])
            if req.temperature > 0.0:
                # single-token fallback in the mixed batch: the sampled
                # draw fed back by the program
                t = int(nxt_h[slot])
                req.output_tokens.append(t)
                self._toks[slot] = t
                self._lens[slot] += 1
                decoded += 1
                touched.append((slot, req))
                continue
            a = int(acc_h[slot])
            emitted = [int(x) for x in greedy_h[slot, :a + 1]]
            if dl:
                # allocator commit of the whole window via the existing
                # append path, then EXACT rollback of the rejected tail
                # (length rewind + table trim down to the reservation)
                rid = req.request_id
                for _ in range(dl + 1):
                    self.allocator.append_token(rid)
                    if self.allocator.last_fork is not None:
                        raise RuntimeError(
                            "speculative append forked a shared block — "
                            "decode writes must only land in private "
                            "blocks")
                if a < dl:
                    self.allocator.rollback(rid, dl - a)
                    self._stats.inc("spec_rollbacks")
                    self.obs.on_rollback(req, dl - a)
                # record() also advances the global serving_spec_* counters
                req._spec.record(dl, a, self.steps)
                self._stats.inc("spec_proposed", dl)
                self._stats.inc("spec_accepted", a)
            req.output_tokens.extend(emitted)
            self._toks[slot] = emitted[-1]
            self._lens[slot] += a + 1
            decoded += len(emitted)
            touched.append((slot, req))
        for slot, req in touched:
            if req.eos_token_id is not None and \
                    req.eos_token_id in req.output_tokens:
                cut = req.output_tokens.index(req.eos_token_id) + 1
                del req.output_tokens[cut:]
                self._finish(req, "stop")
            elif len(req.output_tokens) >= req.max_new_tokens:
                del req.output_tokens[req.max_new_tokens:]
                self._finish(req, "length")
            elif int(self._lens[slot]) >= self.max_model_len:
                self._finish(req, "length")
        for _, req in touched:
            req._progress.set()
        return decoded

    def _flush_pending(self) -> None:
        """Materialize every deferred sampled token (one host transfer for
        all pending ticks), append them in tick order, then run the finish
        checks. eos-bearing requests force a flush per tick, so an eos stop
        is still detected on the exact token that emitted it."""
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        vals = jax.device_get([arr for arr, _ in pending])
        touched = {}
        for arr, (_, items) in zip(vals, pending):
            a = np.asarray(arr)
            for idx, slot, req in items:
                # cancelled mid-flight: its slot may already belong to a
                # NEW request — don't touch output_tokens or _toks[slot]
                if req.state == "finished":
                    continue
                req._pending_n -= 1
                # fused-step overshoot past the token budget: drop
                if len(req.output_tokens) >= req.max_new_tokens:
                    continue
                t = int(a[idx])
                req.output_tokens.append(t)
                self._toks[slot] = t
                touched[req.request_id] = (slot, req)
        for slot, req in touched.values():
            if req.eos_token_id is not None and \
                    req.eos_token_id in req.output_tokens:
                cut = req.output_tokens.index(req.eos_token_id) + 1
                del req.output_tokens[cut:]
                self._finish(req, "stop")
            elif len(req.output_tokens) >= req.max_new_tokens:
                self._finish(req, "length")
            elif int(self._lens[slot]) >= self.max_model_len:
                self._finish(req, "length")
        for _, req in touched.values():
            # wake streaming readers AFTER the finish checks so a reader
            # never observes tokens past an eos truncation
            req._progress.set()

    def _finish(self, req: Request, reason: str) -> None:
        slot = req.slot
        self.sched.finish(req, reason)
        req._pending_n = 0
        if slot is not None:
            self._tables[slot] = 0
            self._lens[slot] = 0
            self._toks[slot] = 0
            self._temps[slot] = 0.0
            if self._dev is not None:
                # the blocks just freed can be reallocated to a request in
                # another slot before this slot is refilled — clear the
                # DEVICE copies too, or the next decode ticks keep writing
                # this dead sequence's K/V into someone else's pages
                d_toks, d_tables, d_lens, d_temps, d_seed = self._dev
                self._dev = (*self._clear_slot_jit()(
                    d_toks, d_tables, d_lens, d_temps, slot), d_seed)
        self.obs.on_finish(req, reason)

    # ------------------------------------------------------------ status
    def snapshot_output(self, req: Request):
        """Consistent (tokens, state, finish_reason) for streaming
        handlers: taken under the engine lock so a reader never races the
        flush's eos truncation."""
        with self._lock:
            return list(req.output_tokens), req.state, req.finish_reason

    def stats(self) -> dict:
        """Legacy JSON snapshot (shape unchanged since r11), now taken
        under the engine lock so a /stats scrape during concurrent
        streaming sees one consistent tick, not a field-by-field race."""
        with self._lock:
            return self._stats_locked()

    def _stats_locked(self) -> dict:
        return {
            "steps": self.steps,
            "kv": self.allocator.occupancy_report(),
            "prefix_cache": self.prefix_cache,
            "prefill_programs": self.prefill_programs,
            "batched_prefills": self.batched_prefills,
            "prefill_tokens": self.prefill_tokens,
            "cow_admissions": self.cow_admissions,
            "dedup_admissions": self.dedup_admissions,
            "speculative": {
                "enabled": self.spec_k > 0,
                "k": self.spec_k,
                "ticks": self.spec_ticks,
                "proposed": self.spec_proposed,
                "accepted": self.spec_accepted,
                "rollbacks": self.spec_rollbacks,
                "acceptance": (self.spec_accepted / self.spec_proposed
                               if self.spec_proposed else 0.0),
            },
            **self.sched.counts(),
        }
