"""Fleet-wide distributed tracing + attempt-attributed SLOs (r19).

r18's FleetRouter made one request's lifetime span multiple replicas —
primary attempt, re-dispatch after death, hedge arms — while r16's
request tracing stopped at a single engine's boundary. This module is
the router-side half that closes the gap:

  * trace-context propagation — the router stamps every engine placement
    with ``{fleet_request_id, attempt, cause}`` (cause in {primary,
    redispatch, hedge}) via ``ServingEngine.submit(trace_ctx=...)``;
    each replica's ``RequestTrace`` bakes the context into its spans, so
    a span anywhere in the fleet says which attempt it served and why
    that attempt existed.
  * router spans — route decisions (with the per-replica ``peek_match``
    probe results that drove them), queue-at-router waits between orphan
    detection and re-placement, breaker transitions, and hedge
    fire/win/cancel, all through the shared ``observability.spans`` ring
    plus the fleet request's own ``RequestTrace``.
  * cross-replica trace merge — ``export_fleet_trace`` assembles router
    spans + every attempt's per-replica ``RequestTrace`` into ONE chrome
    trace: pid=replica lane (pid 0 is the router), tid=decode slot,
    losing hedge arms included and marked ``cancelled``; a re-dispatched
    request renders as a single contiguous waterfall across replicas.
  * attempt-attributed SLOs — always-on histograms labeled
    ``{tier, replica, cause}`` (``fleet_attempt_{route,queue,ttft,e2e}_
    seconds``) plus ``fleet_wasted_decode_tokens_total`` for work thrown
    away by cancelled arms, with fleet-level p50/p95/p99 rollups
    published as ``fleet_slo_seconds{metric,quantile}`` gauges.
  * fleet anomaly detectors — hedge-rate spike, re-dispatch storm,
    breaker flap, sustained cross-replica p95-TTFT skew
    (observability/anomaly.py ``fleet_default_detectors``), fed one
    record per router poll; a detection dumps a flight record embedding
    the router's state (breaker states, registry leases, per-replica
    loads) and the recent requests' merged cross-replica traces.

Threading: the ``on_*`` hooks are invoked by the router under its own
lock; HTTP readers come through ``trace_payload``/``router_state`` which
take only snapshot locks. Span timestamps are real ``monotonic_ns``
regardless of any fake router clock (fake-clock tests assert tags and
counts, never durations).
"""
from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..core.flags import define_flag, get_flag
from ..observability import anomaly as _anomaly
from ..observability import flight_recorder as _flight
from ..observability import spans as _spans
from ..observability.registry import (
    counter as _counter,
    gauge as _gauge,
    histogram as _histogram,
    metrics_enabled,
)
from .observability import chrome_trace_events

define_flag("fleet_flight_requests", 64,
            "Fleet flight-recorder arm: how many settled fleet-request "
            "records (attempt summaries + merged cross-replica traces) "
            "ride along in a fleet anomaly dump, and how far back "
            "GET /trace?id= can answer for finished requests.")
define_flag("fleet_anomaly", "auto",
            "Fleet anomaly detectors (hedge-rate spike, re-dispatch "
            "storm, breaker flap, replica p95-TTFT skew) over per-poll "
            "router records: 'auto' follows FLAGS_anomaly, 'on'/'off' "
            "override it. Needs FLAGS_metrics=on either way.")
define_flag("fleet_detector_window", 16,
            "Rolling window, in router polls, for the fleet anomaly "
            "detectors — breaker transitions are counted per replica "
            "inside this window, and the rate fields feed detectors "
            "bounded by this history.")

_TRUE = ("1", "on", "true", "yes")

# ------------------------------------------------------------- metrics
# Attempt-attributed SLOs: always-on like every fleet_* metric, labeled
# by {tier, replica, cause} so a p95 regression can be blamed on the
# replica AND on why the attempt existed (a slow hedge arm is a very
# different pathology from a slow primary).
_ATT_ROUTE = _histogram("fleet_attempt_route_seconds",
                        "Routing-decision entry to engine arrival, per "
                        "attempt (includes the peek_match probes).",
                        labelnames=("tier", "replica", "cause"),
                        always=True)
_ATT_QUEUE = _histogram("fleet_attempt_queue_seconds",
                        "Engine arrival to prefill start, per attempt.",
                        labelnames=("tier", "replica", "cause"),
                        always=True)
_ATT_TTFT = _histogram("fleet_attempt_ttft_seconds",
                       "Engine arrival to first token, per attempt.",
                       labelnames=("tier", "replica", "cause"),
                       always=True)
_ATT_E2E = _histogram("fleet_attempt_e2e_seconds",
                      "Engine arrival to finish for the WINNING attempt.",
                      labelnames=("tier", "replica", "cause"),
                      always=True)
_WASTED = _counter("fleet_wasted_decode_tokens_total",
                   "Decode tokens thrown away by cancelled attempts "
                   "(losing hedge arms, dead-replica orphans), by "
                   "replica and cancellation cause.",
                   labelnames=("replica", "cause"), always=True)
_SLO_ROLLUP = _gauge("fleet_slo_seconds",
                     "Fleet-level latency rollups: quantiles over the "
                     "merge of every {tier,replica,cause} row of the "
                     "fleet_attempt_*_seconds histograms.",
                     labelnames=("metric", "quantile"), always=True)
_KV_BLOCKS = _counter("fleet_kv_streamed_blocks_total",
                      "KV blocks on the chain-hash transfer wire "
                      "(disaggregated prefill->decode streaming and live "
                      "migration), by ingest outcome: imported (fresh), "
                      "dedup (already resident), rejected (chain-hash "
                      "mismatch), skipped (pool full / after a break).",
                      labelnames=("result",), always=True)
_KV_BYTES = _counter("fleet_kv_streamed_bytes_total",
                     "Raw KV page bytes admitted over the transfer wire "
                     "(fresh imports only — dedups move nothing).",
                     always=True)
_MIGRATIONS = _counter("fleet_migrations_total",
                       "In-flight sessions live-migrated off a draining "
                       "replica onto a survivor.", always=True)
_SCALE_EVENTS = _counter("fleet_scale_events_total",
                         "Elastic fleet membership changes, by "
                         "direction (up = replica joined, down = replica "
                         "retired).", labelnames=("direction",),
                         always=True)

_ROLLUP_SOURCES = (("route", _ATT_ROUTE), ("queue", _ATT_QUEUE),
                   ("ttft", _ATT_TTFT), ("e2e", _ATT_E2E))


def fleet_anomaly_on() -> bool:
    """Fleet detectors run when FLAGS_metrics=on and FLAGS_fleet_anomaly
    says so ('auto' defers to FLAGS_anomaly)."""
    if not metrics_enabled():
        return False
    mode = str(get_flag("fleet_anomaly")).lower()
    if mode in _TRUE:
        return True
    if mode == "auto":
        return str(get_flag("anomaly")).lower() in _TRUE
    return False


def trace_context(fleet_request_id: str, attempt: int,
                  cause: str) -> Dict[str, Any]:
    """The context dict stamped onto every engine placement."""
    return {"fleet_request_id": str(fleet_request_id),
            "attempt": int(attempt), "cause": str(cause)}


class FleetObservability:
    """Router-side observability hub: the FleetRouter calls the ``on_*``
    hooks from its routing/supervision paths; ``tick`` runs once per
    poll and feeds the fleet anomaly detectors."""

    #: per-replica TTFT samples kept for the skew signal
    TTFT_WINDOW = 64
    #: replicas need this many samples before their p95 enters the skew
    SKEW_MIN_SAMPLES = 5

    def __init__(self, router, *, dump: bool = True,
                 dump_cooldown_ticks: int = 50):
        self.router = router
        self.dump = bool(dump)
        self.dump_cooldown_ticks = int(dump_cooldown_ticks)
        self.window = max(int(get_flag("fleet_detector_window")), 1)
        n = max(int(get_flag("fleet_flight_requests")), 1)
        self._lock = threading.Lock()
        self._settled: deque = deque(maxlen=n)   # finished fleet records
        self._breaker_log: deque = deque(maxlen=256)
        self._scale_log: deque = deque(maxlen=256)   # membership changes
        self._ttft: Dict[str, deque] = {}        # rid -> recent TTFTs
        self._tick_n = 0
        self._win_dispatch = 0    # placements since the last tick
        self._win_hedge = 0
        self._win_redispatch = 0
        self._anomaly: Optional[_anomaly.AnomalyEngine] = None
        self._dump_armed_at = -1
        self.dumps: List[str] = []

    # -- dispatch / hedge / breaker hooks (router lock held) ---------------
    def on_dispatch(self, freq, att, probes: List[Dict[str, Any]],
                    t0_ns: int) -> None:
        """One successful engine placement: the route-decision span
        (probe results included) plus, for a re-dispatch, the
        queue-at-router span covering orphan-detection -> re-placement."""
        with self._lock:
            self._win_dispatch += 1
            if att.kind == "redispatch":
                self._win_redispatch += 1
            elif att.kind == "hedge":
                self._win_hedge += 1
        tr = freq.trace
        if tr is None:
            return
        now = time.monotonic_ns()
        if att.kind == "redispatch" and freq._orphan_ns is not None:
            tr.add("fleet.queue", freq._orphan_ns, t0_ns,
                   attempt=att.index, cause=att.kind,
                   fleet_request_id=freq.request_id)
        tr.add("fleet.route", t0_ns, now, attempt=att.index,
               cause=att.kind, chosen=att.replica.rid, probes=probes,
               fleet_request_id=freq.request_id)
        if att.kind == "hedge":
            tr.add("fleet.hedge_fire", now, now, attempt=att.index,
                   hedge_replica=att.replica.rid,
                   fleet_request_id=freq.request_id)

    def on_hedge_win(self, freq, winner) -> None:
        tr = freq.trace
        if tr is not None:
            now = time.monotonic_ns()
            tr.add("fleet.hedge_win", now, now, attempt=winner.index,
                   cause=winner.kind, winner=winner.replica.rid,
                   fleet_request_id=freq.request_id)

    def on_cancelled(self, freq, att, tokens: int, reason: str) -> None:
        """An attempt's partial output was thrown away (losing hedge arm
        or dead-replica orphan): wasted-work accounting + the cancel
        marker span."""
        if tokens > 0:
            _WASTED.inc(int(tokens), replica=att.replica.rid,
                        cause=str(reason))
        tr = freq.trace
        if tr is not None:
            now = time.monotonic_ns()
            tr.add("fleet.hedge_cancel" if reason == "hedge_lost"
                   else "fleet.cancel", now, now, attempt=att.index,
                   cause=att.kind, replica=att.replica.rid,
                   reason=str(reason), wasted_tokens=int(tokens),
                   fleet_request_id=freq.request_id)

    # -- disaggregation / migration / scaling hooks ------------------------
    def on_kv_transfer(self, freq, src: str, dst: str, stats: dict,
                       kind: str = "prefill") -> None:
        """One KV-block transfer over the chain-hash wire (prefill
        streaming or migration): counters by outcome plus a router-lane
        span carrying the full stats."""
        for key in ("imported", "dedup", "rejected", "skipped"):
            n = int(stats.get(key, 0))
            if n:
                _KV_BLOCKS.inc(n, result=key)
        nbytes = int(stats.get("bytes", 0))
        if nbytes:
            _KV_BYTES.inc(nbytes)
        tr = freq.trace
        if tr is not None:
            now = time.monotonic_ns()
            tr.add("fleet.kv_transfer", now, now, src=src, dst=dst,
                   kind=str(kind),
                   **{k: int(stats.get(k, 0)) for k in
                      ("imported", "dedup", "rejected", "skipped",
                       "bytes")},
                   fleet_request_id=freq.request_id)

    def on_migrate(self, freq, src: str, dst: str,
                   stats: Optional[dict]) -> None:
        _MIGRATIONS.inc()
        tr = freq.trace
        if tr is not None:
            now = time.monotonic_ns()
            tr.add("fleet.migrate", now, now, src=src, dst=dst,
                   streamed_blocks=int((stats or {}).get("imported", 0)
                                       + (stats or {}).get("dedup", 0)),
                   fleet_request_id=freq.request_id)

    def on_scale(self, direction: str, rid: str, *, role: str = "any",
                 replicas: int = 0) -> None:
        """Elastic membership change: counter + the scale log merged
        into cross-replica traces as router-lane instants (the breaker
        pattern), + a global span so scrapes and dumps see it."""
        _SCALE_EVENTS.inc(direction=str(direction))
        now_ns = time.monotonic_ns()
        with self._lock:
            self._scale_log.append({
                "ts_ns": now_ns, "ts": time.time(), "tick": self._tick_n,
                "direction": str(direction), "replica": str(rid),
                "role": str(role), "replicas": int(replicas)})
        if _spans.enabled():
            _spans.record_span("fleet.scale", now_ns, now_ns, cat="fleet",
                               args={"direction": str(direction),
                                     "replica": str(rid),
                                     "role": str(role),
                                     "replicas": int(replicas)})

    def scale_log(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{k: v for k, v in ev.items() if k != "ts_ns"}
                    for ev in self._scale_log]

    def on_breaker(self, rid: str, old: Optional[str], new: str) -> None:
        """Breaker state transition (detected at the router's record
        sites and once per poll for time-driven open -> half_open)."""
        now_ns = time.monotonic_ns()
        with self._lock:
            self._breaker_log.append({
                "ts_ns": now_ns, "ts": time.time(), "tick": self._tick_n,
                "replica": rid, "from": old, "to": new})
        if _spans.enabled():
            _spans.record_span("fleet.breaker", now_ns, now_ns,
                               cat="fleet", args={"replica": rid,
                                                  "from": old, "to": new})

    # -- settle -----------------------------------------------------------
    def on_settle(self, freq, winner) -> None:
        """A fleet request finished: attempt-attributed SLO observes for
        every attempt, per-replica TTFT windows for the skew signal, and
        the bounded settled-record ring (merged trace included) that
        backs GET /trace?id= and the fleet flight dumps."""
        with freq._lock:
            atts = list(freq.attempts)
        for att in atts:
            r = att.req
            labels = {"tier": freq.tier, "replica": att.replica.rid,
                      "cause": att.kind}
            if att.route_t0 is not None:
                _ATT_ROUTE.observe(max(0.0, r.arrival_time - att.route_t0),
                                   **labels)
            q = r.queue_seconds()
            if q is not None:
                _ATT_QUEUE.observe(max(0.0, q), **labels)
            t = r.ttft_seconds()
            if t is not None:
                _ATT_TTFT.observe(max(0.0, t), **labels)
                with self._lock:
                    w = self._ttft.get(att.replica.rid)
                    if w is None:
                        w = self._ttft[att.replica.rid] = deque(
                            maxlen=self.TTFT_WINDOW)
                    w.append(float(t))
            if att is winner and r.finish_time is not None:
                _ATT_E2E.observe(max(0.0, r.finish_time - r.arrival_time),
                                 **labels)
        rec: Dict[str, Any] = {
            "kind": "fleet_request", "request_id": freq.request_id,
            "tier": freq.tier, "ts": time.time(),
            "finish_reason": freq.finish_reason,
            "redispatches": freq.redispatches, "hedged": freq.hedged,
            "output_tokens": len(freq.output_tokens),
            "attempts": [dict(att.req.telemetry(), replica=att.replica.rid,
                              cause=att.kind, attempt=att.index,
                              cancelled=att.failed) for att in atts],
        }
        if freq.trace is not None:
            # Keep the freq reference; the merged trace is assembled
            # lazily on first access (GET /trace or a flight dump) so the
            # settle path stays off the serving hot loop.
            rec["_freq"] = freq
        with self._lock:
            self._settled.append(rec)

    # -- per-poll tick -----------------------------------------------------
    def tick(self) -> List[Dict[str, Any]]:
        """One fleet supervision record per router poll: windowed
        hedge/re-dispatch rates, per-replica breaker flap counts, and
        the cross-replica p95-TTFT skew, fed through the fleet anomaly
        detectors (flight dump on detection)."""
        with self._lock:
            self._tick_n += 1
            n = self._tick_n
            dispatches = self._win_dispatch
            hedges = self._win_hedge
            redis = self._win_redispatch
            self._win_dispatch = self._win_hedge = self._win_redispatch = 0
            lo = n - self.window
            flaps: Dict[str, int] = {}
            for ev in self._breaker_log:
                if ev["tick"] >= lo:
                    flaps[ev["replica"]] = flaps.get(ev["replica"], 0) + 1
        rec: Dict[str, Any] = {
            "kind": "fleet_tick", "step": n, "ts": time.time(),
            "inflight": self.router.inflight(),
            "dispatches": dispatches,
            "hedge_rate": hedges / max(1, dispatches),
            "redispatch_rate": redis / max(1, dispatches),
            "breaker_flaps": float(max(flaps.values()) if flaps else 0),
        }
        skew = self._ttft_skew()
        if skew is not None:
            rec["ttft_skew"] = skew
        if n % 8 == 1 and metrics_enabled():
            self.publish_rollups()
        return self.observe_record(rec)

    def observe_record(self, rec: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Feed one fleet record through the detectors; dump on
        detection. Public seam — tests and obsbench inject synthetic
        records through the same path tick() uses."""
        engine = self._anomaly_engine()
        if engine is None:
            return []
        events = engine.observe(rec)
        if events and self.dump:
            self._maybe_dump(events)
        return events

    def _anomaly_engine(self) -> Optional[_anomaly.AnomalyEngine]:
        if self._anomaly is None and fleet_anomaly_on():
            self._anomaly = _anomaly.AnomalyEngine(
                _anomaly.fleet_default_detectors(window=self.window),
                dump=False)
        return self._anomaly

    def anomalies_recent(self, n: int = 16) -> List[Dict[str, Any]]:
        return [] if self._anomaly is None else self._anomaly.recent(n)

    def _ttft_skew(self) -> Optional[float]:
        with self._lock:
            windows = {rid: list(w) for rid, w in self._ttft.items()}
        p95s = []
        for w in windows.values():
            if len(w) < self.SKEW_MIN_SAMPLES:
                continue
            s = sorted(w)
            p95s.append(s[min(len(s) - 1, int(0.95 * len(s)))])
        if len(p95s) < 2:
            return None
        mx, mn = max(p95s), min(p95s)
        if mn <= 0:
            return None
        return mx / mn

    def _maybe_dump(self, events: List[Dict[str, Any]]) -> None:
        if self._tick_n <= self._dump_armed_at:
            return
        self._dump_armed_at = self._tick_n + self.dump_cooldown_ticks
        with self._lock:
            settled = list(self._settled)
            transitions = list(self._breaker_log)
        requests = []
        for rec in settled:
            out = {k: v for k, v in rec.items() if k != "_freq"}
            trace = self._materialize_trace(rec)
            if trace is not None:
                out["trace"] = trace
            requests.append(out)
        extra = {
            "anomaly": events[0],
            "fleet_anomalies": events,
            "router": self.router_state(),
            "fleet_requests": requests,
            "breaker_transitions": [
                {k: v for k, v in t.items() if k != "ts_ns"}
                for t in transitions],
        }
        try:
            path = _flight.get_flight_recorder().dump(
                f"fleet_{events[0]['kind']}", extra=extra)
            self.dumps.append(path)
        except OSError:
            pass

    # -- router state (flight dumps + debugging) ---------------------------
    def router_state(self) -> Dict[str, Any]:
        """Breaker states, registry leases, per-replica loads — the
        'why was the router doing that' context a flight dump embeds."""
        r = self.router
        reps: Dict[str, Any] = {}
        for rid, rep in r.replicas.items():
            age = r.registry.heartbeat_age(rid)
            reps[rid] = {
                "breaker": rep.breaker.state,
                "draining": bool(rep.draining),
                "dead": r.replica_dead(rep),
                "load": rep.load(),
                "queue_depth": rep.queue_depth(),
                "lease_age_s": (round(age, 4) if math.isfinite(age)
                                else None),
            }
        return {"inflight": r.inflight(), "replicas": reps}

    def publish_rollups(self) -> Dict[str, Dict[str, float]]:
        """Fleet-level p50/p95/p99 rollups across every label row of the
        attempt histograms, published as fleet_slo_seconds gauges (the
        FleetServer refreshes them on every /metrics scrape)."""
        out: Dict[str, Dict[str, float]] = {}
        for metric, h in _ROLLUP_SOURCES:
            qs = h.rollup_quantiles()
            clean = {k: v for k, v in qs.items()
                     if v is not None and not math.isnan(v)}
            if clean:
                out[metric] = clean
                for qname, v in clean.items():
                    _SLO_ROLLUP.set(v, metric=metric, quantile=qname)
        return out

    # -- cross-replica trace merge ----------------------------------------
    def merged_trace_events(self, freq) -> List[Dict[str, Any]]:
        """Router spans + every attempt's per-replica RequestTrace as one
        chrome-trace event list: pid 0 = router, pid i+1 = replica-i
        lane, tid = decode slot; cancelled arms (hedge losers, orphans)
        are tagged ``cancelled`` on every span. A synthetic
        ``fleet.attempt`` umbrella span per attempt (engine arrival ->
        finish/cancel) keeps the waterfall contiguous across the engine
        tick gaps."""
        with freq._lock:
            atts = list(freq.attempts)
        rids = list(self.router.replicas.keys())
        events: List[Dict[str, Any]] = []
        procs: Dict[int, str] = {0: "router"}
        if freq.trace is not None:
            events += chrome_trace_events(
                list(freq.trace.spans), pid=0, tid=0,
                extra_args={"fleet_request_id": freq.request_id})
        for att in atts:
            rid = att.replica.rid
            pid = rids.index(rid) + 1 if rid in rids else len(rids) + 1
            procs[pid] = rid
            tr = att.req.trace
            extra = {"fleet_request_id": freq.request_id,
                     "attempt": att.index, "cause": att.kind}
            if att.failed:
                extra["cancelled"] = True
            tid = tr.slot if (tr is not None and tr.slot is not None) else 0
            if tr is not None:
                events += chrome_trace_events(list(tr.spans), pid=pid,
                                              tid=tid, extra_args=extra)
            r = att.req
            b_ns = int(r.arrival_time * 1e9)
            end = (r.finish_time if r.finish_time is not None
                   else time.monotonic())
            e_ns = int(end * 1e9)
            if tr is not None and tr.spans:
                # the engine's finish/cancel hook can run a beat after
                # finish_time (end of the tick): keep the umbrella over
                # every span the attempt actually recorded
                e_ns = max(e_ns, max(s["end_ns"] for s in tr.spans))
                b_ns = min(b_ns, min(s["begin_ns"] for s in tr.spans))
            events.append({
                "name": "fleet.attempt", "ph": "X", "cat": "fleet",
                "ts": b_ns / 1e3, "dur": max(e_ns - b_ns, 0) / 1e3,
                "pid": pid, "tid": tid,
                "args": dict(extra, request_id=freq.request_id,
                             replica=rid, state=r.state,
                             finish_reason=r.finish_reason)})
        # breaker transitions on replicas this request touched, inside
        # its own time window, land on the router lane as instants
        if events:
            lo = min(e["ts"] for e in events)
            hi = max(e["ts"] + e["dur"] for e in events)
            att_rids = {a.replica.rid for a in atts}
            with self._lock:
                translog = list(self._breaker_log)
                scalelog = list(self._scale_log)
            for ev in translog:
                ts = ev["ts_ns"] / 1e3
                if ev["replica"] in att_rids and lo <= ts <= hi:
                    events.append({
                        "name": "fleet.breaker", "ph": "X", "cat": "fleet",
                        "ts": ts, "dur": 0.0, "pid": 0, "tid": 0,
                        "args": {"fleet_request_id": freq.request_id,
                                 "replica": ev["replica"],
                                 "from": ev["from"], "to": ev["to"]}})
            # scale events are fleet-wide: any membership change inside
            # this request's window lands on its router lane
            for ev in scalelog:
                ts = ev["ts_ns"] / 1e3
                if lo <= ts <= hi:
                    events.append({
                        "name": "fleet.scale", "ph": "X", "cat": "fleet",
                        "ts": ts, "dur": 0.0, "pid": 0, "tid": 0,
                        "args": {"fleet_request_id": freq.request_id,
                                 "direction": ev["direction"],
                                 "replica": ev["replica"],
                                 "replicas": ev["replicas"]}})
        for pid in sorted(procs):
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "tid": 0, "args": {"name": procs[pid]}})
        return events

    def trace_payload(self, request_id: str) -> Optional[Dict[str, Any]]:
        """The merged chrome trace for one fleet request id — assembled
        live for in-flight requests, served from the settled ring for
        finished ones. None when unknown (or the request was never
        traced)."""
        rid = str(request_id)
        freq = None
        with self.router._lock:
            freq = self.router._inflight.get(rid)
        if freq is not None and freq.trace is not None:
            return {"traceEvents": self.merged_trace_events(freq),
                    "displayTimeUnit": "ms"}
        with self._lock:
            target = None
            for rec in reversed(self._settled):
                if rec["request_id"] == rid:
                    target = rec
                    break
        if target is not None:
            trace = self._materialize_trace(target)
            if trace is not None:
                return {"traceEvents": trace, "displayTimeUnit": "ms"}
        return None

    def _materialize_trace(
            self, rec: Dict[str, Any]) -> Optional[List[Dict[str, Any]]]:
        """Assemble (and cache) a settled record's merged trace from the
        retained freq reference. None when the request was never traced."""
        trace = rec.get("trace")
        if trace is None and rec.get("_freq") is not None:
            trace = self.merged_trace_events(rec["_freq"])
            with self._lock:
                rec["trace"] = trace
        return trace


def export_fleet_trace(router, request_id: str, path: str) -> str:
    """Write one fleet request's merged cross-replica chrome trace
    (chrome://tracing / Perfetto). Raises ValueError when the request is
    unknown or was never traced (FLAGS_metrics off at submit)."""
    import json

    payload = router.obs.trace_payload(request_id)
    if payload is None:
        raise ValueError(
            f"fleet request {request_id!r} has no merged trace (unknown id, "
            "evicted from the settled ring, or FLAGS_metrics was off at "
            "submit)")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f)
    return path


def coverage_of(events: List[Dict[str, Any]]) -> float:
    """Fraction of a merged trace's wall window (first span begin ->
    last span end) covered by the union of its span intervals — the
    obsbench completeness gate ('no invisible time')."""
    ivals = sorted((e["ts"], e["ts"] + e["dur"]) for e in events
                   if e.get("ph") == "X")
    if not ivals:
        return 0.0
    lo = ivals[0][0]
    hi = max(e for _, e in ivals)
    if hi <= lo:
        return 1.0
    covered = 0.0
    cur_lo, cur_hi = ivals[0]
    for b, e in ivals[1:]:
        if b > cur_hi:
            covered += cur_hi - cur_lo
            cur_lo, cur_hi = b, e
        else:
            cur_hi = max(cur_hi, e)
    covered += cur_hi - cur_lo
    return covered / (hi - lo)


def unparented_spans(events: List[Dict[str, Any]],
                     request_id: str) -> List[Dict[str, Any]]:
    """Spans in a merged trace that lost their attribution: every real
    span must name the fleet request it belongs to, and every
    replica-lane span must carry attempt/cause tags."""
    bad = []
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        owner = args.get("fleet_request_id", args.get("request_id"))
        if owner != request_id:
            bad.append(e)
        elif e.get("pid", 0) != 0 and ("attempt" not in args
                                       or "cause" not in args):
            bad.append(e)
    return bad
