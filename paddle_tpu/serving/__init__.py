"""Production serving runtime: paged KV cache + continuous batching.

Reference analogs: the serving stack around fused_multi_transformer
(PaddleNLP llm serving) and the TPU ragged-paged-attention line of work
(PAPERS.md: "Ragged Paged Attention: A High-Performance and Flexible LLM
Inference Kernel for TPU").

The static-batch decode path (models/generation.py) allocates one
[b, max_len] KV ring per generate() call: every sequence pays max_len of
HBM whether it uses it or not, finished sequences keep decoding as padding
until the whole batch drains, and a new request waits for the NEXT batch.
This package replaces that with the vLLM/TPU-serving shape:

  * blocks.py    — fixed-size token blocks carved from one preallocated
                   pool; per-sequence block tables; O(1) alloc/append/free
                   with immediate reuse; occupancy/fragmentation gauges in
                   the observability metrics registry.
  * paged.py     — the device-side paged KV pool ([num_blocks, block_size,
                   kv_heads, head_dim] per layer) + the PagedLayerCache
                   view the models' attention layers consume; prefill
                   scatter of a contiguous prefix into pages.
  * scheduler.py — continuous batching: admits queued requests into the
                   running decode batch every step, interleaves bounded
                   prefill chunks with decode steps, evicts finished
                   sequences (and frees their blocks) immediately.
  * engine.py    — ServingEngine: one compiled decode step over a fixed
                   set of slots (paged ragged attention, sampling inside
                   the program, page buffers donated), chunked prefill,
                   works unchanged with the int8 weight-only swap.
  * server.py    — stdlib HTTP front end (POST /generate) with
                   per-request telemetry: queue time, TTFT, tokens/s;
                   FleetServer exposes the same protocol over a
                   FleetRouter (plus /drain for rolling restarts).
  * fleet.py     — FleetRouter: prefix-cache-aware routing across N
                   replicas, heartbeat-lease failure detection + circuit
                   breakers, re-dispatch of in-flight requests off dead
                   replicas (bitwise-identical greedy output), hedged
                   retries past a TTFT deadline, graceful drain, and
                   fleet-level load shedding with jittered Retry-After.
  * fleet_proc.py — process-granularity replicas: each replica is a
                   supervised OS subprocess (own model + engine + HTTP
                   server) spoken to over the server.py wire protocol;
                   crash/hang/zombie survival via waitpid + heartbeat-
                   lease death detection, capped+jittered respawn, a
                   warm-up routing gate, and incarnation fence tokens.
  * speculative.py — draft-model-free self-speculation: n-gram prompt-
                   lookup drafting from each request's own history plus
                   the per-request adaptive-k throttle; the engine
                   verifies drafts in ONE multi-token dispatch and rolls
                   rejected positions back exactly.
  * observability.py — per-request lifecycle traces (chrome-trace
                   exportable), tier-labeled SLO histograms (TTFT, TPOT,
                   queue, e2e), goodput/shed counters, per-tick engine
                   gauges, serving anomaly detectors + the flight-
                   recorder arm that auto-dumps on regression.
  * fleet_observability.py — fleet-wide distributed tracing: router-
                   stamped trace context (attempt/cause) on every
                   placement, cross-replica merged chrome traces
                   (pid=replica, tid=slot), attempt-attributed SLO
                   histograms with fleet rollups, and fleet anomaly
                   detectors (hedge spike, re-dispatch storm, breaker
                   flap, replica TTFT skew) with router-state dumps.
"""
from .blocks import BlockAllocator  # noqa: F401
from .observability import (  # noqa: F401
    RequestTrace,
    ServingObservability,
    export_request_trace,
)
from .paged import PagedKVPool, PagedLayerCache  # noqa: F401
from .scheduler import Request, Scheduler  # noqa: F401
from .speculative import NgramDrafter, SpecState  # noqa: F401
from .engine import (  # noqa: F401
    EngineDrainingError,
    QueueFullError,
    ServingEngine,
)
from .fleet import (  # noqa: F401
    CircuitBreaker,
    FleetAutoscaler,
    FleetRequest,
    FleetRouter,
    Replica,
    build_fleet,
    parse_fleet_roles,
)
from .fleet_observability import (  # noqa: F401
    FleetObservability,
    export_fleet_trace,
)
from .fleet_proc import (  # noqa: F401
    ProcessReplica,
    ProcessReplicaSpec,
    build_process_fleet,
    wait_fleet_ready,
)
from .server import FleetServer, ServingServer  # noqa: F401

__all__ = [
    "BlockAllocator",
    "CircuitBreaker",
    "EngineDrainingError",
    "FleetAutoscaler",
    "FleetObservability",
    "FleetRequest",
    "FleetRouter",
    "FleetServer",
    "NgramDrafter",
    "PagedKVPool",
    "PagedLayerCache",
    "ProcessReplica",
    "ProcessReplicaSpec",
    "QueueFullError",
    "Replica",
    "Request",
    "RequestTrace",
    "Scheduler",
    "ServingEngine",
    "ServingObservability",
    "ServingServer",
    "SpecState",
    "build_fleet",
    "build_process_fleet",
    "parse_fleet_roles",
    "export_fleet_trace",
    "wait_fleet_ready",
    "export_request_trace",
]
