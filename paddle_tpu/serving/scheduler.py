"""Continuous-batching scheduler (host-side policy, no device code).

Static batching decodes a fixed batch until EVERY row finishes: short
requests pad out to the longest, and arrivals wait for the next batch. Here
requests flow through three states instead:

    queued ──admit──▶ prefill ──first token──▶ running ──eos/len──▶ finished

and the engine calls one `Scheduler` tick per decode step, so:

  * admission happens BETWEEN decode steps — a new request joins the
    running batch as soon as a slot and KV blocks are available;
  * prefill is chunked and interleaved with decode (one bounded chunk per
    tick), so a long prompt cannot stall the running batch's tokens for
    more than one chunk's worth of compute;
  * a finished sequence's blocks are freed (and its slot reopened)
    IMMEDIATELY, before the next admission check.

Admission uses worst-case KV reservation: a request is admitted only when
`blocks_for(min(prompt + max_new_tokens, max_model_len))` blocks fit beside
every admitted request's reservation. Decode-time block appends therefore
NEVER fail mid-flight — no preemption/swap machinery is needed (the trade
is admission conservatism, i.e. occupancy, not correctness).

The same reservation covers SPECULATIVE (up-to-k-token) ticks: the engine
caps every draft at the remaining `max_new_tokens` budget and at
`max_model_len - 1 - current_len`, so a verify window can never commit a
token past the reserved worst case, and rollback only ever shrinks usage
back toward it (BlockAllocator.rollback never trims below the
reservation).
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional

from ..observability.registry import counter as _counter, gauge as _gauge

_ADMITTED = _counter("serving_requests_admitted_total",
                     "Requests admitted into the running batch.",
                     always=True)
_FINISHED = _counter("serving_requests_finished_total",
                     "Requests finished (by reason).",
                     labelnames=("reason",), always=True)
_QUEUED = _gauge("serving_queue_depth", "Requests waiting for admission.",
                 always=True)
_RUNNING = _gauge("serving_running_sequences",
                  "Sequences in prefill or decode.", always=True)

_req_counter = itertools.count()


class Request:
    """One generation request and its lifecycle telemetry. Timestamps are
    time.monotonic(); the engine fills them as the request moves through
    the pipeline (queue time = prefill_start - arrival, TTFT =
    first_token - arrival)."""

    def __init__(self, prompt: List[int], max_new_tokens: int = 16,
                 temperature: float = 0.0, eos_token_id: Optional[int] = None,
                 request_id: Optional[str] = None, tier: str = "default",
                 trace_ctx: Optional[dict] = None,
                 prefill_only: bool = False):
        self.request_id = (request_id if request_id is not None
                           else f"req-{next(_req_counter)}")
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.eos_token_id = eos_token_id
        # admission tier: the SLO-metric label (one class today; the
        # fleet router's priority tiers plug in here)
        self.tier = str(tier) if tier else "default"
        # per-request lifecycle trace, attached by the engine at submit
        # when span recording is on (serving/observability.RequestTrace)
        self.trace = None
        # distributed trace context stamped by the FleetRouter: which
        # fleet request / attempt / cause this engine-level placement
        # serves — RequestTrace inherits it so every span is attributed
        self.trace_ctx = dict(trace_ctx) if trace_ctx else None
        # disaggregated serving: compute + register + keep the prompt's KV
        # blocks, then finish with reason "prefill_complete" WITHOUT
        # sampling a first token — the blocks are exported to a decode
        # replica instead of decoded locally
        self.prefill_only = bool(prefill_only)
        self.output_tokens: List[int] = []
        self.state = "queued"
        self.finish_reason: Optional[str] = None
        self.slot: Optional[int] = None
        self.arrival_time = time.monotonic()
        self.prefill_start: Optional[float] = None
        self.first_token_time: Optional[float] = None
        self.finish_time: Optional[float] = None
        # engine-owned prefill progress (tokens of prompt already run)
        self.prefill_pos = 0
        self.prefix_matched = 0       # prompt tokens served from the cache
        self._cow_src = None          # shared block forked at admission
        self._ws_caches = None        # contiguous prefill workspace
        self._pending_n = 0           # sampled tokens not yet fetched
        self._reserved_blocks = 0
        # self-speculation state, attached by the engine when spec is on
        # (greedy requests only); kept after finish for telemetry
        self._drafter = None          # speculative.NgramDrafter
        self._spec = None             # speculative.SpecState
        self._done = threading.Event()  # set at finish (HTTP waiters)
        self._progress = threading.Event()  # pulsed per output flush

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def wait_progress(self, timeout: Optional[float] = None) -> bool:
        """Block until more output tokens were flushed (or the request
        finished). Streaming handlers clear + re-wait in a loop."""
        return self._progress.wait(timeout)

    # -- telemetry --------------------------------------------------------
    def queue_seconds(self) -> Optional[float]:
        if self.prefill_start is None:
            return None
        return self.prefill_start - self.arrival_time

    def ttft_seconds(self) -> Optional[float]:
        if self.first_token_time is None:
            return None
        return self.first_token_time - self.arrival_time

    def decode_tokens_per_s(self) -> Optional[float]:
        if self.finish_time is None or self.first_token_time is None:
            return None
        n = len(self.output_tokens)
        dt = self.finish_time - self.first_token_time
        return (n - 1) / dt if n > 1 and dt > 0 else None

    def telemetry(self) -> dict:
        t = {
            "request_id": self.request_id,
            "tier": self.tier,
            "state": self.state,
            "finish_reason": self.finish_reason,
            "prompt_tokens": len(self.prompt),
            "prefix_matched_tokens": self.prefix_matched,
            "output_tokens": len(self.output_tokens),
            "queue_s": self.queue_seconds(),
            "ttft_s": self.ttft_seconds(),
            "decode_tok_s": self.decode_tokens_per_s(),
        }
        if self._spec is not None:
            t["spec_proposed"] = self._spec.proposed
            t["spec_accepted"] = self._spec.accepted
            t["spec_acceptance"] = self._spec.acceptance
        return t


class Scheduler:
    """Owns request state transitions + slot/block accounting. The engine
    drives it: admit() between decode steps, next_prefill() for chunked
    prefill work, start_running()/finish() on transitions."""

    def __init__(self, allocator, max_slots: int, max_model_len: int):
        self.allocator = allocator
        self.max_slots = int(max_slots)
        self.max_model_len = int(max_model_len)
        self.waiting: Deque[Request] = deque()
        self.prefilling: List[Request] = []
        self.running: Dict[int, Request] = {}   # slot -> request
        self._free_slots = list(range(self.max_slots - 1, -1, -1))
        self._reserved_blocks = 0

    # -- intake -----------------------------------------------------------
    def submit(self, req: Request) -> None:
        if len(req.prompt) + 1 > self.max_model_len:
            raise ValueError(
                f"prompt of {len(req.prompt)} tokens leaves no room under "
                f"max_model_len={self.max_model_len}")
        if len(req.prompt) == 0:
            raise ValueError("empty prompt")
        self.waiting.append(req)
        self._publish()

    def _worst_case_blocks(self, req: Request) -> int:
        total = min(len(req.prompt) + req.max_new_tokens, self.max_model_len)
        return self.allocator.blocks_for(total)

    # -- per-tick transitions ---------------------------------------------
    def admit(self) -> List[Request]:
        """Move waiting requests into prefill while a slot AND a worst-case
        KV reservation fit (FCFS — no request starves). The gate is on the
        SUFFIX worst case: blocks whose prefix already sits in the cache
        cost nothing, which raises effective capacity under shared-prefix
        load."""
        admitted = []
        while self.waiting and self._free_slots:
            req = self.waiting[0]
            total = min(len(req.prompt) + req.max_new_tokens,
                        self.max_model_len)
            if not self.allocator.can_reserve_prefix(req.prompt, total):
                break
            self.waiting.popleft()
            req.slot = self._free_slots.pop()
            # materialize the whole worst-case reservation as the block
            # table NOW: decode-time appends never allocate, so the engine
            # can upload each sequence's table once and leave it alone.
            # The table's head is any cached shared prefix; the engine
            # prefils only from req.prefill_pos (= matched tokens).
            _, matched, cow_src, new_blocks = self.allocator.reserve_prefix(
                req.request_id, req.prompt, total)
            req.prefix_matched = matched
            req.prefill_pos = matched
            req._cow_src = cow_src
            req._reserved_blocks = new_blocks
            self._reserved_blocks += new_blocks
            req.state = "prefill"
            req.prefill_start = time.monotonic()
            self.prefilling.append(req)
            admitted.append(req)
            _ADMITTED.inc()
        self._publish()
        return admitted

    def next_prefill(self) -> Optional[Request]:
        """The request that should get this tick's prefill chunk (FCFS;
        one bounded chunk per tick keeps decode latency bounded)."""
        return self.prefilling[0] if self.prefilling else None

    def start_running(self, req: Request) -> None:
        """Prefill done (first token sampled, prefix scattered to pages)."""
        self.prefilling.remove(req)
        req.state = "running"
        req.first_token_time = time.monotonic()
        self.running[req.slot] = req
        self._publish()

    def finish(self, req: Request, reason: str) -> None:
        """Evict: free blocks + slot immediately (the next admit() sees
        them), whatever state the request was in."""
        if req.state == "queued":
            # cancel/timeout of a never-admitted request: drop it from the
            # queue, or admit() would later re-admit a finished request
            try:
                self.waiting.remove(req)
            except ValueError:
                pass
        elif req.state == "prefill":
            self.prefilling.remove(req)
        elif req.state == "running":
            self.running.pop(req.slot, None)
        if req.slot is not None:
            self._free_slots.append(req.slot)
            req.slot = None
        if req.request_id in self.allocator.sequences():
            self.allocator.free(req.request_id)
        self._reserved_blocks -= req._reserved_blocks
        req._reserved_blocks = 0
        req._ws_caches = None
        req._cow_src = None
        req.state = "finished"
        req.finish_reason = reason
        req.finish_time = time.monotonic()
        req._done.set()
        req._progress.set()   # wake streaming readers for the final drain
        _FINISHED.inc(reason=reason)
        self._publish()

    # -- introspection ----------------------------------------------------
    def has_work(self) -> bool:
        return bool(self.waiting or self.prefilling or self.running)

    def counts(self) -> dict:
        return {"waiting": len(self.waiting),
                "prefilling": len(self.prefilling),
                "running": len(self.running),
                "free_slots": len(self._free_slots),
                "reserved_blocks": self._reserved_blocks}

    def _publish(self):
        _QUEUED.set(len(self.waiting))
        _RUNNING.set(len(self.prefilling) + len(self.running))
