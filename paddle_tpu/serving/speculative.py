"""Draft-model-free self-speculation for the serving engine.

Two small host-side pieces (no device code here):

``NgramDrafter`` — prompt-lookup drafting (PAPERS.md: the
"assisted generation" / prompt-lookup line): find the longest recent
n-gram in the request's OWN token history (prompt + everything emitted)
that matches the current suffix, and propose the tokens that followed its
previous occurrence. The index is incremental — each gram length keeps a
dict of gram-tuple -> position-after-last-occurrence, extended from a
watermark as history grows (history only grows: drafts never enter it
until verified) — so a propose() call is O(new_tokens * n_lengths), not
O(history).

``SpecState`` — per-request adaptive-k throttle. Acceptance feedback
shrinks/grows the draft length between 1 and the configured cap, and a
run of consecutive fruitless ticks (no match, or zero accepted) pauses
drafting entirely for a fixed number of ticks before probing again, so
non-repetitive traffic degrades to the plain one-token decode path
instead of paying verify-window dispatches that never accept.

The engine consumes these in its speculative tick (engine._decode_step):
draft -> ONE batched verify dispatch over the k+1-token window ->
longest-accepted-prefix commit -> exact rollback of the rejected tail
(BlockAllocator.rollback + device length rewind).
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

from ..observability.registry import counter as _counter

# process-wide speculation counters (always on, like every serving_*
# metric): SpecState.record is the single choke point every verify tick
# passes through, so the global accounting lives here rather than being
# re-derived in the engine
_SPEC_PROPOSED = _counter("serving_spec_proposed_total",
                          "Draft tokens offered to speculative "
                          "verification.", always=True)
_SPEC_ACCEPTED = _counter("serving_spec_accepted_total",
                          "Draft tokens accepted by speculative "
                          "verification.", always=True)
_SPEC_ROLLBACKS = _counter("serving_spec_rollbacks_total",
                           "Speculative ticks that rejected >= 1 draft "
                           "token (exact KV rollback).", always=True)


class NgramDrafter:
    """Incremental n-gram lookup over one request's token history.

    Grams of length ``min_n``..``max_n`` are indexed by the position just
    AFTER their occurrence; lookups try the longest suffix first. The
    current suffix itself is never indexed (endings stop one short of the
    history length), so a match always points at a strictly earlier
    occurrence.
    """

    def __init__(self, max_n: int = 3, min_n: int = 2):
        if min_n < 1:
            raise ValueError("min_n must be >= 1")
        self.min_n = int(min_n)
        self.max_n = max(int(max_n), self.min_n)
        self._index: Dict[int, Dict[Tuple[int, ...], int]] = {
            n: {} for n in range(self.min_n, self.max_n + 1)}
        self._upto = 0  # gram endings < _upto are already indexed

    def propose(self, toks: Sequence[int], k: int) -> List[int]:
        """Draft up to k tokens continuing ``toks`` (may return fewer, or
        none when no suffix recurs). ``toks`` must extend the history seen
        by earlier calls — the drafter is per-request state."""
        T = len(toks)
        if k <= 0 or T <= self.min_n:
            return []
        for end in range(max(self._upto, self.min_n), T):
            for n in range(self.min_n, min(self.max_n, end) + 1):
                self._index[n][tuple(toks[end - n:end])] = end
        self._upto = max(self._upto, T)
        for n in range(min(self.max_n, T - 1), self.min_n - 1, -1):
            p = self._index[n].get(tuple(toks[T - n:]))
            if p is not None:
                # the match says history repeats with period T - p from p;
                # extrapolate cyclically so a draft is never truncated just
                # because the latest occurrence sits close to the end
                # (constant or short-cycle tails would otherwise cap the
                # draft at the period instead of k)
                period = T - p
                return [toks[p + (i % period)] for i in range(k)]
        return []


class SpecState:
    """Adaptive draft-length throttle + per-request speculation counters.

    ``draft_k(tick)`` is the length the engine should draft this tick
    (0 = paused). ``record(proposed, accepted, tick)`` feeds acceptance
    back: full/high acceptance grows k toward the cap, a rejected window
    halves it (a no-match tick leaves k alone — it carries no evidence
    about draft quality), and ``miss_limit`` consecutive fruitless ticks
    pause drafting for ``pause_ticks`` engine ticks. After the pause, ONE
    fruitless probe re-pauses immediately with the pause doubled (capped
    at 8x), so a non-repetitive request converges to near-zero
    speculation overhead; decent acceptance (>= 1/4 of the window)
    resets the backoff, while a chance low-acceptance window on
    otherwise-random text leaves it armed.
    """

    def __init__(self, k_max: int, pause_ticks: int = 32,
                 miss_limit: int = 4):
        self.k_max = max(1, int(k_max))
        self.k = self.k_max
        self.pause_ticks = int(pause_ticks)
        self.miss_limit = max(1, int(miss_limit))
        self.proposed = 0          # lifetime draft tokens offered
        self.accepted = 0          # lifetime draft tokens verified
        self.rollbacks = 0         # ticks that rejected >= 1 draft token
        self._miss = 0
        self._resume_tick = 0
        self._pause = self.pause_ticks    # current backoff value

    def draft_k(self, tick: int) -> int:
        return 0 if tick < self._resume_tick else self.k

    def record(self, proposed: int, accepted: int, tick: int) -> None:
        self.proposed += proposed
        self.accepted += accepted
        if proposed:
            _SPEC_PROPOSED.inc(proposed)
        if accepted:
            _SPEC_ACCEPTED.inc(accepted)
        if proposed and accepted < proposed:
            self.rollbacks += 1
            _SPEC_ROLLBACKS.inc()
        if accepted == 0:
            self._miss += 1
            if proposed:
                # a dispatched-and-rejected window is real evidence
                # against the draft source; a mere no-match tick is not
                self.k = max(1, self.k // 2)
            if self._miss >= self.miss_limit:
                self._resume_tick = tick + self._pause
                # exponential backoff: each fruitless probe doubles the
                # next pause (capped), and re-pauses after ONE miss — a
                # non-repetitive request converges to ~zero spec overhead
                self._pause = min(self._pause * 2, 8 * self.pause_ticks)
                self._miss = self.miss_limit - 1
        else:
            if accepted * 4 >= proposed:
                self._miss = 0
                self._pause = self.pause_ticks
            # a LOW-acceptance window (< 1/4 of the draft) leaves the
            # backoff armed: random text throws up chance n-gram repeats
            # whose windows accept a token or two, and letting each lucky
            # hit re-enable miss_limit fresh probes keeps adversarial
            # traffic paying verify dispatches forever
            if accepted * 2 >= proposed:
                self.k = min(self.k_max, self.k + 1)
            else:
                self.k = max(1, self.k - 1)

    @property
    def acceptance(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0
