"""Device-side paged KV pool + the cache view the models consume.

The pool is ONE preallocated array pair per layer:

    k_pages, v_pages : [num_blocks, block_size, kv_heads, head_dim]

Block ids from blocks.BlockAllocator index the leading dim directly. A
sequence's KV lives in the (non-contiguous) blocks its table names; the
ragged paged attention op (ops/pallas/paged_attention.py) computes straight
from (pages, block_table, context_lens) without ever materializing a
contiguous per-sequence cache.

PagedLayerCache is the per-layer view threaded through the models' existing
`caches=` plumbing: gpt/llama attention layers duck-type on `.block_table`
to pick the paged decode path over the static-ring path. It is constructed
inside the compiled decode step (engine.py), so its fields are Tensors of
traced values.
"""
from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp


class PagedLayerCache:
    """Per-layer paged-KV view: pages + the batch's block tables/lengths.

    seq_lens counts tokens ALREADY in the cache for each slot (the new
    token of the current decode step is written at position seq_lens and
    included in attention by the op)."""

    __slots__ = ("k_pages", "v_pages", "block_table", "seq_lens")

    def __init__(self, k_pages, v_pages, block_table, seq_lens):
        self.k_pages = k_pages
        self.v_pages = v_pages
        self.block_table = block_table
        self.seq_lens = seq_lens


class PagedKVPool:
    """Owns the per-layer page arrays. Holds plain jax arrays (not Tensors):
    the compiled decode step takes and returns them as donated buffers."""

    def __init__(self, num_blocks: int, block_size: int, num_layers: int,
                 num_kv_heads: int, head_dim: int, dtype=jnp.float32):
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.num_layers = int(num_layers)
        self.num_kv_heads = int(num_kv_heads)
        self.head_dim = int(head_dim)
        self.dtype = dtype
        shape = (self.num_blocks, self.block_size, self.num_kv_heads,
                 self.head_dim)
        self.layers: List[Tuple[jax.Array, jax.Array]] = [
            (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in range(self.num_layers)
        ]

    def nbytes(self) -> int:
        k, _ = self.layers[0]
        return 2 * self.num_layers * k.size * k.dtype.itemsize

    def replace(self, new_layers) -> None:
        """Swap in the page arrays a compiled step returned (the old ones
        were donated into it)."""
        self.layers = [(k, v) for k, v in new_layers]


def write_prefix(k_pages, v_pages, k, v, table, *, block_size):
    """Scatter a contiguous KV prefix into its pages.

    k, v: [plen_padded, kv_heads, d] with plen_padded a multiple of
    block_size; table: [plen_padded // block_size] int32 block ids.
    Garbage rows past the real prompt length land in the tail of the last
    block — they are masked by context_lens until the decode steps that
    overwrite them. Used by the engine after chunked prefill (which runs in
    a contiguous workspace); jit-compiled per padded length."""
    nb = table.shape[0]
    kb = k.reshape(nb, block_size, k.shape[1], k.shape[2])
    vb = v.reshape(nb, block_size, v.shape[1], v.shape[2])
    return (k_pages.at[table].set(kb.astype(k_pages.dtype)),
            v_pages.at[table].set(vb.astype(v_pages.dtype)))


def append_token_kv(k_pages, v_pages, k_new, v_new, block_table, seq_lens,
                    *, block_size):
    """Write one new token's K/V per slot at its current position.

    k_new, v_new: [slots, kv_heads, d]; block_table: [slots, max_blocks];
    seq_lens: [slots] tokens already present (write position). Idle slots
    point at the null block and write garbage there harmlessly."""
    slots = seq_lens.shape[0]
    page = jnp.take_along_axis(
        block_table, (seq_lens // block_size)[:, None], axis=1)[:, 0]
    off = seq_lens % block_size
    k_pages = k_pages.at[page, off].set(k_new.astype(k_pages.dtype))
    v_pages = v_pages.at[page, off].set(v_new.astype(v_pages.dtype))
    return k_pages, v_pages
