"""Paged KV-cache block allocator (host-side bookkeeping) with automatic
prefix caching.

The device pool (paged.py) is a fixed array of NUM_BLOCKS fixed-size token
blocks; this allocator owns which block belongs to which sequence. The free
list is a stack (LIFO reuse keeps recently-touched blocks hot), a sequence's
block table is an append-only list, and free() releases the whole table in
one pass.

Block 0 is reserved as the NULL block: inactive decode slots point their
block tables at it so the compiled decode step can write the (masked,
garbage) KV of idle slots somewhere harmless without branching. The null
block is never handed out and never cached.

Prefix caching (vLLM-style, over FULL blocks only):

  * every block is refcounted; a block may appear in several sequences'
    tables at once (shared prompt prefix) — refcount == number of tables
    (plus copy-on-write pins) holding it;
  * a sequence's prompt is chain-hashed per full block (blake2b over the
    previous block's digest + this block's token ids), so a block's key
    identifies the whole prefix up to and including it;
  * `register_prefix` publishes a finished prefill's full prompt blocks
    into the hash index; `reserve_prefix` looks new prompts up and returns
    a table whose head is the shared cached blocks — the engine prefils
    only the unmatched suffix;
  * when a sequence's refcount on a hashed block drops to zero the block is
    NOT returned to the free list: it parks in an LRU pool of evictable
    cached blocks, still indexed, still matchable. Capacity pressure
    reclaims from the LRU tail only after the free list is empty;
  * a write may never land in a block another reader can see: full blocks
    are immutable by construction (only partial tail blocks are written,
    and those are never hashed/shared), and the one exception — a prompt
    that is ENTIRELY cached, whose re-decoded last token would land in the
    final shared block — is handled by copy-on-write: `reserve_prefix`
    forks that block (fresh private block in the table, the shared source
    pinned until the sequence finishes so the engine can copy its device
    contents before any eviction).

Occupancy/fragmentation are surfaced through the observability metrics
registry (always-on gauges — serving runs don't require FLAGS_metrics):

  serving_kv_blocks_total / _used / _free   pool shape
  serving_kv_cached_blocks                  evictable cached (refcount-0)
  serving_kv_tokens                         live tokens across sequences
  serving_kv_occupancy                      used blocks / allocatable blocks
  serving_kv_fragmentation                  1 - tokens/(used * block_size)

Gauge publication is O(1): running counters, never a sum over sequences.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..observability.registry import counter as _counter, gauge as _gauge

_BLOCKS_TOTAL = _gauge("serving_kv_blocks_total",
                       "KV pool size in blocks (excl. the null block).",
                       always=True)
_BLOCKS_USED = _gauge("serving_kv_blocks_used",
                      "KV blocks currently assigned to sequences.",
                      always=True)
_BLOCKS_FREE = _gauge("serving_kv_blocks_free",
                      "KV blocks on the free list.", always=True)
_BLOCKS_CACHED = _gauge("serving_kv_cached_blocks",
                        "Evictable prefix-cache blocks (hashed, refcount 0).",
                        always=True)
_TOKENS = _gauge("serving_kv_tokens",
                 "Live KV tokens across all sequences.", always=True)
_OCCUPANCY = _gauge("serving_kv_occupancy",
                    "used / allocatable KV blocks.", always=True)
_FRAG = _gauge("serving_kv_fragmentation",
               "1 - tokens/(used*block_size): tail waste of partially "
               "filled last blocks.", always=True)
_PREFIX_HITS = _counter("serving_prefix_cache_hits_total",
                        "Admissions that matched >=1 cached prefix block.",
                        always=True)
_PREFIX_MISSES = _counter("serving_prefix_cache_misses_total",
                          "Admissions that matched no cached block.",
                          always=True)
_PREFIX_HIT_TOKENS = _counter("serving_prefix_hit_tokens_total",
                              "Prompt tokens served from the prefix cache "
                              "(prefill skipped).", always=True)
_PREFIX_EVICTIONS = _counter("serving_prefix_evictions_total",
                             "Cached blocks reclaimed under capacity "
                             "pressure.", always=True)
_PREFIX_DEDUPS = _counter("serving_prefix_dedup_blocks_total",
                          "Private prefilled blocks swapped for an "
                          "already-indexed twin at register time.",
                          always=True)
_PREFIX_IMPORTS = _counter("serving_prefix_imported_blocks_total",
                           "Streamed KV blocks admitted into the cache "
                           "after chain-hash verification.", always=True)
_PREFIX_IMPORT_DEDUPS = _counter("serving_prefix_import_dedup_total",
                                 "Streamed blocks whose digest was already "
                                 "resident (idempotent no-op).", always=True)


class BlockAllocator:
    """Host-side allocator over a pool of `num_blocks` blocks of
    `block_size` tokens each. Block ids index the device pool directly."""

    NULL_BLOCK = 0

    def __init__(self, num_blocks: int, block_size: int,
                 prefix_cache: bool = True):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (one is the null block)")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.prefix_cache = bool(prefix_cache)
        # stack: LIFO reuse; block 0 reserved (never handed out)
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._tables: Dict[object, List[int]] = {}
        self._lens: Dict[object, int] = {}
        # refcounts for LIVE blocks only (block in >=1 table or pinned)
        self._ref: Dict[int, int] = {}
        # content addressing: block -> chain digest, digest -> block. A
        # hashed block keeps its digest while live AND while evictable;
        # both maps drop the entry together on eviction.
        self._digest: Dict[int, bytes] = {}
        self._index: Dict[bytes, int] = {}
        # refcount-0 hashed blocks, LRU order (oldest first = evict first)
        self._evictable: "OrderedDict[int, None]" = OrderedDict()
        # copy-on-write source pins: seq_id -> blocks held alive beyond the
        # table so the engine can device-copy them before any eviction
        self._extra: Dict[object, List[int]] = {}
        self._tokens = 0            # running sum of _lens (O(1) publish)
        # table size at reservation: rollback never truncates below it (a
        # worst-case reservation must survive speculation intact)
        self._base: Dict[object, int] = {}
        self.last_fork: Optional[Tuple[int, int]] = None
        # register_prefix dedup swaps: [(table_index, private, canonical)]
        self.last_dedup: List[Tuple[int, int, int]] = []
        self._publish()

    # -- capacity ---------------------------------------------------------
    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return len(self._ref)

    @property
    def cached_blocks(self) -> int:
        return len(self._evictable)

    @property
    def available_blocks(self) -> int:
        """Blocks a new reservation can claim: free + evictable cached."""
        return len(self._free) + len(self._evictable)

    def blocks_for(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.block_size)  # ceil div

    def can_allocate(self, n_tokens: int) -> bool:
        return self.blocks_for(n_tokens) <= self.available_blocks

    # -- content addressing -----------------------------------------------
    def chain_digest(self, prev: bytes, tokens) -> bytes:
        """One link of the chain hash: commits to `prev` (the previous
        full block's digest, b"" at the chain head) plus this block's
        token ids — so a digest identifies the whole prefix up to and
        including its block, and a receiver can verify a streamed block
        against nothing but the preceding digest and the claimed tokens."""
        h = hashlib.blake2b(prev, digest_size=16)
        for t in tokens:
            h.update(int(t).to_bytes(8, "little", signed=True))
        return h.digest()

    def block_hashes(self, tokens) -> List[bytes]:
        """Chain digests for every FULL block of `tokens`: digest i commits
        to tokens[0 : (i+1)*block_size], so equal digests imply equal whole
        prefixes (not just equal blocks)."""
        out: List[bytes] = []
        prev = b""
        bs = self.block_size
        for i in range(len(tokens) // bs):
            prev = self.chain_digest(prev, tokens[i * bs:(i + 1) * bs])
            out.append(prev)
        return out

    # -- KV-block streaming (disaggregated serving / live migration) -------
    def export_prefix(self, tokens) -> List[dict]:
        """Wire metadata for the RESIDENT full-block prefix of `tokens`:
        one record per indexed full block, in chain order, stopping at the
        first full block that is not in the index. Each record carries the
        chain digest, the previous link's digest, the block's token ids,
        and the local block id (so a caller that owns the device pool can
        attach the block's KV bytes). Read-only — no refcounts move."""
        out: List[dict] = []
        prev = b""
        bs = self.block_size
        for i in range(len(tokens) // bs):
            blk_tokens = [int(t) for t in tokens[i * bs:(i + 1) * bs]]
            key = self.chain_digest(prev, blk_tokens)
            blk = self._index.get(key)
            if blk is None:
                break
            out.append({"digest": key, "prev": prev, "block": blk,
                        "tokens": blk_tokens})
            prev = key
        return out

    def import_block(self, prev_digest: bytes, tokens,
                     digest: bytes) -> Tuple[int, bool]:
        """Admit one streamed FULL block into the cache. The chain digest
        is recomputed from `prev_digest` + `tokens` and must equal the
        claimed `digest` — a corrupted or mislabeled block is rejected
        (ValueError) before it can poison the index. Returns
        `(block_id, imported)`:

          * already-resident digest -> `(existing_block, False)`: the
            transfer is an idempotent no-op (its LRU position refreshes so
            a chain being streamed can't evict its own head);
          * otherwise a blank block is claimed (free stack, then LRU
            eviction) and published directly into the evictable cached
            pool — refcount 0, matchable, reclaimable — and the caller
            must scatter the block's KV bytes into the device pool at
            `block_id` before any reservation can match it.

        Conservation holds by construction: the block moves free/evicted ->
        evictable. Raises MemoryError when no blank block exists."""
        if not self.prefix_cache:
            raise ValueError("prefix cache disabled: an imported block "
                             "could never be matched")
        if len(tokens) != self.block_size:
            raise ValueError(f"imported block carries {len(tokens)} tokens, "
                             f"expected a full block of {self.block_size}")
        want = self.chain_digest(prev_digest, tokens)
        if want != bytes(digest):
            raise ValueError("chain-hash mismatch: streamed block rejected "
                             "(corrupt payload or broken chain)")
        blk = self._index.get(want)
        if blk is not None:
            if blk in self._evictable:
                self._evictable.move_to_end(blk)
            _PREFIX_IMPORT_DEDUPS.inc()
            return blk, False
        blk = self._pop_block()
        self._digest[blk] = want
        self._index[want] = blk
        self._evictable[blk] = None      # newest at the LRU tail
        _PREFIX_IMPORTS.inc()
        self._publish()
        return blk, True

    def _match(self, tokens) -> List[int]:
        """Longest run of cached blocks covering a prefix of `tokens`."""
        if not self.prefix_cache:
            return []
        matched: List[int] = []
        for key in self.block_hashes(tokens):
            blk = self._index.get(key)
            if blk is None:
                break
            matched.append(blk)
        return matched

    def peek_match(self, tokens) -> int:
        """Prompt tokens a reservation would serve from cache (no side
        effects; scheduler admission gating)."""
        m = len(self._match(tokens))
        return min(m * self.block_size, len(tokens))

    def blocks_needed(self, tokens, total_tokens: int) -> int:
        """NEW blocks a reserve_prefix() would claim from the pool (the
        suffix worst case, +1 when a full-prompt match forks its last
        block). Excludes revived cached blocks — those were already
        resident."""
        plen = len(tokens)
        matched = self._match(tokens)
        m = len(matched)
        need = self.blocks_for(max(int(total_tokens), plen, 1)) - m
        if m and m * self.block_size >= plen:
            need += 1   # copy-on-write fork of the last shared block
        return need

    def can_reserve_prefix(self, tokens, total_tokens: int) -> bool:
        """Admission gate: do the suffix's new blocks fit beside the
        matched blocks that must be revived out of the evictable pool?"""
        matched = self._match(tokens)
        revive = sum(1 for b in matched if b in self._evictable)
        plen = len(tokens)
        m = len(matched)
        need = self.blocks_for(max(int(total_tokens), plen, 1)) - m
        if m and m * self.block_size >= plen:
            need += 1
        return need + revive <= self.available_blocks

    # -- block pool internals ---------------------------------------------
    def _pop_block(self) -> int:
        """A blank block: the free stack first, then evict the LRU cached
        block (dropping its index entry — the prefix is gone)."""
        if self._free:
            return self._free.pop()
        if self._evictable:
            blk, _ = self._evictable.popitem(last=False)   # oldest first
            key = self._digest.pop(blk)
            del self._index[key]
            _PREFIX_EVICTIONS.inc()
            return blk
        raise MemoryError("KV pool exhausted")

    def _claim(self, need: int) -> List[int]:
        if need > self.available_blocks:
            raise MemoryError(
                f"KV pool exhausted: need {need} blocks, "
                f"{self.available_blocks} available")
        out = []
        for _ in range(need):
            blk = self._pop_block()
            self._ref[blk] = 1
            out.append(blk)
        return out

    def _decref(self, blk: int) -> bool:
        """Drop one reference; True when the block left the live set."""
        n = self._ref[blk] - 1
        if n > 0:
            self._ref[blk] = n
            return False
        del self._ref[blk]
        if blk in self._digest and self.prefix_cache:
            self._evictable[blk] = None          # newest at the LRU tail
        else:
            self._free.append(blk)
        return True

    def _revive(self, blk: int) -> None:
        """Take a matched block live (cached -> referenced, or +1 ref)."""
        if blk in self._ref:
            self._ref[blk] += 1
        else:
            del self._evictable[blk]
            self._ref[blk] = 1

    # -- lifecycle --------------------------------------------------------
    def allocate(self, seq_id, n_tokens: int) -> List[int]:
        """Claim blocks for a new sequence of `n_tokens` (prefill). Returns
        the block table. Raises KeyError on duplicate id, MemoryError when
        the pool can't hold it (callers queue the request instead)."""
        if seq_id in self._tables:
            raise KeyError(f"sequence {seq_id!r} already allocated")
        need = self.blocks_for(max(int(n_tokens), 1))
        table = self._claim(need)
        self._tables[seq_id] = table
        self._lens[seq_id] = int(n_tokens)
        self._tokens += int(n_tokens)
        self._base[seq_id] = len(table)
        self._publish()
        return table

    def reserve(self, seq_id, n_tokens: int, total_tokens: int) -> List[int]:
        """allocate(), but claim blocks for `total_tokens` (worst case)
        upfront while the live length starts at `n_tokens`. The table never
        grows mid-decode, so the serving engine uploads it to the device
        ONCE at admission and never touches it again — no per-step
        allocator call, no per-step table scatter. Costs nothing in
        capacity when admission already gates on the worst case."""
        if seq_id in self._tables:
            raise KeyError(f"sequence {seq_id!r} already allocated")
        need = self.blocks_for(max(int(total_tokens), int(n_tokens), 1))
        table = self._claim(need)
        self._tables[seq_id] = table
        self._lens[seq_id] = int(n_tokens)
        self._tokens += int(n_tokens)
        self._base[seq_id] = len(table)
        self._publish()
        return table

    def reserve_prefix(self, seq_id, tokens,
                       total_tokens: int) -> Tuple[List[int], int,
                                                   Optional[int], int]:
        """reserve(), but the table's head reuses cached blocks matching
        the prompt's full-block prefix. Returns
        `(table, matched_tokens, cow_src, new_blocks)`:

          * `matched_tokens` — prompt tokens whose KV is already resident;
            the engine prefils only `tokens[matched_tokens:]`;
          * `cow_src` — when the ENTIRE prompt matched, the engine enters
            decode directly and its first write would land in the last
            shared block: that table entry is a fresh private fork and
            `cow_src` is the shared source to device-copy from (pinned
            until free(seq_id) so concurrent admissions can't evict it);
          * `new_blocks` — blocks claimed from the pool (suffix worst case
            + fork), the number capacity actually shrank by.
        """
        if seq_id in self._tables:
            raise KeyError(f"sequence {seq_id!r} already allocated")
        plen = len(tokens)
        matched = self._match(tokens)
        m = len(matched)
        total = self.blocks_for(max(int(total_tokens), plen, 1))
        full_match = bool(m) and m * self.block_size >= plen
        need = total - m + (1 if full_match else 0)
        revive = sum(1 for b in matched if b in self._evictable)
        if need + revive > self.available_blocks:
            raise MemoryError(
                f"KV pool exhausted: need {need} blocks beside {revive} "
                f"revivals, {self.available_blocks} available")
        # revive FIRST: _pop_block must never evict a block we matched
        for blk in matched:
            self._revive(blk)
        fresh = self._claim(need)
        cow_src: Optional[int] = None
        if full_match:
            # fork the last shared block: the fresh block takes its table
            # slot, the source stays referenced (pinned outside the table)
            # until this sequence finishes so the engine can copy its
            # device contents without racing an eviction
            cow_src = matched[-1]
            table = matched[:-1] + [fresh[0]] + fresh[1:]
            self._extra.setdefault(seq_id, []).append(cow_src)
        else:
            table = matched + fresh
        self._tables[seq_id] = table
        self._lens[seq_id] = plen
        self._tokens += plen
        self._base[seq_id] = len(table)
        matched_tokens = min(m * self.block_size, plen)
        if m:
            _PREFIX_HITS.inc()
            _PREFIX_HIT_TOKENS.inc(matched_tokens)
        elif self.prefix_cache:
            _PREFIX_MISSES.inc()
        self._publish()
        return table, matched_tokens, cow_src, need

    def register_prefix(self, seq_id, tokens) -> int:
        """Publish a prefilled prompt's full blocks into the hash index so
        later prompts can share them. Call AFTER the prefix KV has been
        scattered into the pool pages. Idempotent. When a block's content
        key is ALREADY indexed under a different block (two identical
        prompts prefilled concurrently), the private duplicate is swapped
        for the canonical block — live dedup: the table adopts the
        canonical block, the duplicate returns to the free list, and the
        swap is recorded in `self.last_dedup` as
        `(table_index, private_blk, canonical_blk)` so a caller that owns
        device state can redirect its block-table row. Returns how many
        blocks were newly indexed."""
        if not self.prefix_cache:
            return 0
        table = self._tables[seq_id]
        added = 0
        self.last_dedup = []
        for i, key in enumerate(self.block_hashes(tokens)):
            blk = table[i]
            if blk == self.NULL_BLOCK or blk in self._digest:
                continue
            canon = self._index.get(key)
            if canon is not None and canon != blk:
                # identical content prefilled twice: share from now on.
                # The private block was claimed fresh (refcount 1, never
                # hashed), so the decref sends it straight to the free
                # stack. The canonical block may be parked evictable.
                self._revive(canon)
                table[i] = canon
                self._decref(blk)
                self.last_dedup.append((i, blk, canon))
                _PREFIX_DEDUPS.inc()
                continue
            self._digest[blk] = key
            self._index[key] = blk
            added += 1
        if self.last_dedup:
            self._publish()
        return added

    def rollback(self, seq_id, n_tokens: int) -> List[int]:
        """Rewind a sequence by `n_tokens` (speculative-decode rejection).
        The live length shrinks and any blocks appended PAST the original
        reservation that the shorter length no longer needs are released —
        the reservation itself (`reserve*`'s worst case) is never
        truncated, so a mid-flight sequence keeps its admission guarantee.
        Returns the (possibly trimmed) block table. The rejected tail's
        device KV is left in place as garbage masked by the length — full
        blocks are immutable/shared by construction, so rejected writes
        only ever landed in this sequence's private blocks."""
        n = int(n_tokens)
        if n < 0:
            raise ValueError("rollback count must be >= 0")
        if n == 0:
            return self._tables[seq_id]
        if n > self._lens[seq_id]:
            raise ValueError(
                f"rollback of {n} exceeds live length {self._lens[seq_id]}")
        table = self._tables[seq_id]
        new_len = self._lens[seq_id] - n
        keep = max(self.blocks_for(max(new_len, 1)),
                   self._base.get(seq_id, 0))
        while len(table) > keep:
            self._decref(table.pop())
        self._lens[seq_id] = new_len
        self._tokens -= n
        self._publish()
        return table

    def append_token(self, seq_id) -> List[int]:
        """Account one decoded token; grows the block table by one block
        when the sequence crosses a block boundary, and copy-on-write forks
        the destination block if it is shared (refcount > 1) or published
        in the prefix index — a write must never be visible to another
        reader. The fork is recorded in `self.last_fork = (src, dst)` so a
        caller that owns device state can copy the contents. Raises
        MemoryError when a needed block isn't there — the scheduler
        preempts or queues in that case."""
        table = self._tables[seq_id]
        n = self._lens[seq_id] + 1
        self.last_fork = None
        if self.blocks_for(n) > len(table):
            if not self.available_blocks:
                raise MemoryError("KV pool exhausted on append")
            blk = self._pop_block()
            self._ref[blk] = 1
            table.append(blk)
        else:
            bi = (n - 1) // self.block_size   # block receiving this token
            blk = table[bi]
            if self._ref.get(blk, 0) > 1 or blk in self._digest:
                dst = self._pop_block()
                self._ref[dst] = 1
                table[bi] = dst
                self._decref(blk)
                self.last_fork = (blk, dst)
        self._lens[seq_id] = n
        self._tokens += 1
        self._publish()
        return table

    def free(self, seq_id) -> int:
        """Release a sequence's references. Unhashed blocks whose refcount
        hits zero go straight back to the free stack (immediate LIFO
        reuse); hashed blocks park in the evictable LRU pool, still
        matchable. Returns how many blocks left the live set."""
        table = self._tables.pop(seq_id)
        self._tokens -= self._lens.pop(seq_id)
        self._base.pop(seq_id, None)
        released = 0
        for blk in reversed(table):      # LIFO: reuse hottest first
            released += self._decref(blk)
        for blk in self._extra.pop(seq_id, ()):
            released += self._decref(blk)
        self._publish()
        return released

    # -- introspection ----------------------------------------------------
    def table(self, seq_id) -> List[int]:
        return list(self._tables[seq_id])

    def seq_len(self, seq_id) -> int:
        return self._lens[seq_id]

    def sequences(self):
        return list(self._tables)

    def refcount(self, blk: int) -> int:
        return self._ref.get(blk, 0)

    def check_invariants(self) -> None:
        """Conservation + sharing invariants (tests call this after every
        mutation sequence; cheap enough for production asserts too)."""
        allocatable = self.num_blocks - 1
        live = set(self._ref)
        ev = set(self._evictable)
        free = set(self._free)
        assert not (live & ev) and not (live & free) and not (ev & free), \
            "a block is in two pools at once"
        assert len(live) + len(ev) + len(free) == allocatable, \
            f"conservation violated: {len(live)}+{len(ev)}+{len(free)} " \
            f"!= {allocatable}"
        assert self.NULL_BLOCK not in live | ev | free
        assert self.NULL_BLOCK not in self._digest
        # refcount >= number of live readers
        readers: Dict[int, int] = {}
        for t in self._tables.values():
            for b in t:
                readers[b] = readers.get(b, 0) + 1
        for pins in self._extra.values():
            for b in pins:
                readers[b] = readers.get(b, 0) + 1
        for b, r in readers.items():
            assert self._ref.get(b, 0) == r, \
                f"block {b}: refcount {self._ref.get(b, 0)} != {r} readers"
        assert set(readers) == live
        # index <-> digest are inverse bijections over hashed blocks
        assert {v: k for k, v in self._index.items()} == self._digest
        assert ev <= set(self._digest)
        assert self._tokens == sum(self._lens.values())

    def conservation_ok(self) -> bool:
        """O(1) conservation law: every allocatable block is in exactly one
        of live / evictable / free. False means a leak or double-free (KV
        corruption follows) — the serving anomaly engine samples this per
        tick; check_invariants() is the O(n) forensic version."""
        return (len(self._ref) + len(self._evictable) + len(self._free)
                == self.num_blocks - 1)

    def occupancy_report(self) -> dict:
        """Pool shape + occupancy/fragmentation, the dict the metrics
        gauges mirror (and servebench embeds in its report)."""
        allocatable = self.num_blocks - 1
        used = self.used_blocks
        tokens = self._tokens
        cap = used * self.block_size
        return {
            "conservation_ok": self.conservation_ok(),
            "num_blocks": allocatable,
            "block_size": self.block_size,
            "used_blocks": used,
            "free_blocks": len(self._free),
            "cached_blocks": len(self._evictable),
            "sequences": len(self._tables),
            "tokens": tokens,
            "occupancy": used / allocatable if allocatable else 0.0,
            # shared blocks can make per-sequence token sums exceed the
            # unique-block capacity; clamp at 0 (no tail waste)
            "fragmentation": max(0.0, 1.0 - tokens / cap) if cap else 0.0,
        }

    def _publish(self):
        # O(1): running counters only — never a sum over sequences
        allocatable = self.num_blocks - 1
        used = len(self._ref)
        cap = used * self.block_size
        _BLOCKS_TOTAL.set(allocatable)
        _BLOCKS_USED.set(used)
        _BLOCKS_FREE.set(len(self._free))
        _BLOCKS_CACHED.set(len(self._evictable))
        _TOKENS.set(self._tokens)
        _OCCUPANCY.set(used / allocatable if allocatable else 0.0)
        _FRAG.set(max(0.0, 1.0 - self._tokens / cap) if cap else 0.0)

    def __repr__(self):  # pragma: no cover
        r = self.occupancy_report()
        return (f"BlockAllocator(blocks={r['used_blocks']}/"
                f"{r['num_blocks']}, cached={r['cached_blocks']}, "
                f"seqs={r['sequences']}, occ={r['occupancy']:.2f})")
