"""Paged KV-cache block allocator (host-side bookkeeping).

The device pool (paged.py) is a fixed array of NUM_BLOCKS fixed-size token
blocks; this allocator owns which block belongs to which sequence. All
operations are O(1) amortized: the free list is a stack (LIFO reuse keeps
recently-touched blocks hot), a sequence's block table is an append-only
list, and free() pushes the whole table back in one pass.

Block 0 is reserved as the NULL block: inactive decode slots point their
block tables at it so the compiled decode step can write the (masked,
garbage) KV of idle slots somewhere harmless without branching.

Occupancy/fragmentation are surfaced through the observability metrics
registry (always-on gauges — serving runs don't require FLAGS_metrics):

  serving_kv_blocks_total / _used / _free   pool shape
  serving_kv_tokens                         live tokens across sequences
  serving_kv_occupancy                      used blocks / allocatable blocks
  serving_kv_fragmentation                  1 - tokens/(used * block_size)
                                            (internal fragmentation: tail
                                            waste of partially-filled last
                                            blocks)
"""
from __future__ import annotations

from typing import Dict, List

from ..observability.registry import gauge as _gauge

_BLOCKS_TOTAL = _gauge("serving_kv_blocks_total",
                       "KV pool size in blocks (excl. the null block).",
                       always=True)
_BLOCKS_USED = _gauge("serving_kv_blocks_used",
                      "KV blocks currently assigned to sequences.",
                      always=True)
_BLOCKS_FREE = _gauge("serving_kv_blocks_free",
                      "KV blocks on the free list.", always=True)
_TOKENS = _gauge("serving_kv_tokens",
                 "Live KV tokens across all sequences.", always=True)
_OCCUPANCY = _gauge("serving_kv_occupancy",
                    "used / allocatable KV blocks.", always=True)
_FRAG = _gauge("serving_kv_fragmentation",
               "1 - tokens/(used*block_size): tail waste of partially "
               "filled last blocks.", always=True)


class BlockAllocator:
    """Host-side allocator over a pool of `num_blocks` blocks of
    `block_size` tokens each. Block ids index the device pool directly."""

    NULL_BLOCK = 0

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (one is the null block)")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # stack: LIFO reuse; block 0 reserved (never handed out)
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._tables: Dict[object, List[int]] = {}
        self._lens: Dict[object, int] = {}
        self._publish()

    # -- capacity ---------------------------------------------------------
    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    def blocks_for(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.block_size)  # ceil div

    def can_allocate(self, n_tokens: int) -> bool:
        return self.blocks_for(n_tokens) <= len(self._free)

    # -- lifecycle --------------------------------------------------------
    def allocate(self, seq_id, n_tokens: int) -> List[int]:
        """Claim blocks for a new sequence of `n_tokens` (prefill). Returns
        the block table. Raises KeyError on duplicate id, MemoryError when
        the pool can't hold it (callers queue the request instead)."""
        if seq_id in self._tables:
            raise KeyError(f"sequence {seq_id!r} already allocated")
        need = self.blocks_for(max(int(n_tokens), 1))
        if need > len(self._free):
            raise MemoryError(
                f"KV pool exhausted: need {need} blocks, {len(self._free)} "
                f"free")
        table = [self._free.pop() for _ in range(need)]
        self._tables[seq_id] = table
        self._lens[seq_id] = int(n_tokens)
        self._publish()
        return table

    def reserve(self, seq_id, n_tokens: int, total_tokens: int) -> List[int]:
        """allocate(), but claim blocks for `total_tokens` (worst case)
        upfront while the live length starts at `n_tokens`. The table never
        grows mid-decode, so the serving engine uploads it to the device
        ONCE at admission and never touches it again — no per-step
        allocator call, no per-step table scatter. Costs nothing in
        capacity when admission already gates on the worst case."""
        if seq_id in self._tables:
            raise KeyError(f"sequence {seq_id!r} already allocated")
        need = self.blocks_for(max(int(total_tokens), int(n_tokens), 1))
        if need > len(self._free):
            raise MemoryError(
                f"KV pool exhausted: need {need} blocks, {len(self._free)} "
                f"free")
        table = [self._free.pop() for _ in range(need)]
        self._tables[seq_id] = table
        self._lens[seq_id] = int(n_tokens)
        self._publish()
        return table

    def append_token(self, seq_id) -> List[int]:
        """Account one decoded token; grows the block table by one block
        when the sequence crosses a block boundary. Returns the (possibly
        grown) table. Raises MemoryError when a needed block isn't there —
        the scheduler preempts or queues in that case."""
        table = self._tables[seq_id]
        n = self._lens[seq_id] + 1
        if self.blocks_for(n) > len(table):
            if not self._free:
                raise MemoryError("KV pool exhausted on append")
            table.append(self._free.pop())
        self._lens[seq_id] = n
        self._publish()
        return table

    def free(self, seq_id) -> int:
        """Release a sequence's blocks back to the pool (immediate reuse).
        Returns how many blocks were released."""
        table = self._tables.pop(seq_id)
        self._lens.pop(seq_id)
        self._free.extend(reversed(table))  # LIFO: reuse hottest first
        self._publish()
        return len(table)

    # -- introspection ----------------------------------------------------
    def table(self, seq_id) -> List[int]:
        return list(self._tables[seq_id])

    def seq_len(self, seq_id) -> int:
        return self._lens[seq_id]

    def sequences(self):
        return list(self._tables)

    def occupancy_report(self) -> dict:
        """Pool shape + occupancy/fragmentation, the dict the metrics
        gauges mirror (and servebench embeds in its report)."""
        allocatable = self.num_blocks - 1
        used = self.used_blocks
        tokens = sum(self._lens.values())
        cap = used * self.block_size
        return {
            "num_blocks": allocatable,
            "block_size": self.block_size,
            "used_blocks": used,
            "free_blocks": len(self._free),
            "sequences": len(self._tables),
            "tokens": tokens,
            "occupancy": used / allocatable if allocatable else 0.0,
            "fragmentation": 1.0 - tokens / cap if cap else 0.0,
        }

    def _publish(self):
        r = self.occupancy_report()
        _BLOCKS_TOTAL.set(r["num_blocks"])
        _BLOCKS_USED.set(r["used_blocks"])
        _BLOCKS_FREE.set(r["free_blocks"])
        _TOKENS.set(r["tokens"])
        _OCCUPANCY.set(r["occupancy"])
        _FRAG.set(r["fragmentation"])

    def __repr__(self):  # pragma: no cover
        r = self.occupancy_report()
        return (f"BlockAllocator(blocks={r['used_blocks']}/"
                f"{r['num_blocks']}, seqs={r['sequences']}, "
                f"occ={r['occupancy']:.2f})")
