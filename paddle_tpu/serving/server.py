"""Stdlib HTTP front end for the serving engine (POST /generate).

Same shape as observability/serve.py's MetricsServer: one
ThreadingHTTPServer + daemon threads, no third-party web stack. The server
owns the engine loop thread — handler threads only submit requests and
block on the request's completion event, so concurrent clients are batched
CONTINUOUSLY by the single engine loop rather than serialized.

  POST /generate   {"prompt": [int, ...], "max_new_tokens": 16,
                    "temperature": 0.0, "eos_token_id": null}
               ->  {"request_id", "output_tokens", "finish_reason",
                    "telemetry": {queue_s, ttft_s, decode_tok_s, ...}}
                   With "stream": true the response is chunked
                   transfer-encoding NDJSON: one {"request_id", "tokens",
                   "done": false} line per flushed token batch (the
                   engine's deferred-fetch flush points), then a final
                   {"done": true, "finish_reason", "telemetry"} line.
                   A client disconnect cancels the request (its slot and
                   KV reservation return to the pool immediately).
  POST /kv/export  {"tokens": [...]} -> NDJSON: one line per resident
                   full prompt block (chain digest + base64 page bytes)
  POST /kv/ingest  that NDJSON -> {"imported", "dedup", "rejected",
                   "skipped", "bytes"}; chain-hash verified, idempotent
                   (disaggregated prefill->decode streaming + live KV
                   migration ride this wire)
  GET  /stats      engine + KV-pool occupancy snapshot (JSON), taken in
                   ONE engine-lock acquisition so concurrent streaming
                   never yields a torn scrape
  GET  /metrics    the process-wide metrics registry as Prometheus text
                   (observability/serve.py renders it) — TTFT/TPOT/queue
                   histograms, goodput/shed counters, KV-pool gauges
  GET  /healthz    engine health snapshot: 200 {"ok": true, status,
                   steps, last_tick_age_s, ...} / 503 when the engine
                   loop is dead, a serving anomaly fired recently, or
                   the engine has work but hasn't ticked (stale) —
                   load-balancer semantics, body says why

Every response carries the request's own telemetry (queue time, TTFT,
steady-state decode tokens/s); the aggregate gauges/histograms live in the
observability metrics registry (serving_* metrics, always on). With
FLAGS_serving_metrics_port > 0 the same /metrics + training-side /healthz
are ALSO served on a dedicated port (one scrape target per concern).
"""
from __future__ import annotations

import base64
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs

from ..core.flags import define_flag, get_flag
from ..observability import serve as _obs_serve
from . import observability as _sobs  # noqa: F401 — defines the flags
from .engine import QueueFullError

define_flag("serving_port", 0,
            "Port for the serving HTTP front end (POST /generate); 0 binds "
            "an ephemeral port.")
define_flag("serving_request_timeout_s", 300.0,
            "Per-request wall-clock cap for POST /generate before the "
            "server answers 504.")


# -------------------------------------------- KV-block wire format
# One NDJSON line per streamed block, chain order:
#   {"digest": hex, "prev": hex, "tokens": [int, ...],
#    "layers": [[k_b64, v_b64], ...]}
# — exactly engine.export_kv_blocks()'s records with the raw page bytes
# base64'd. The receiver re-derives every digest from (prev, tokens)
# before admitting anything, so a corrupted or mislabeled line is
# rejected rather than poisoning the prefix cache.

def kv_wire_encode(records) -> bytes:
    lines = [json.dumps({
        "digest": r["digest"], "prev": r["prev"], "tokens": r["tokens"],
        "layers": [[base64.b64encode(k).decode("ascii"),
                    base64.b64encode(v).decode("ascii")]
                   for k, v in r["layers"]],
    }) for r in records]
    return ("\n".join(lines) + "\n").encode() if lines else b""


def kv_wire_decode(body: bytes):
    records = []
    for line in body.splitlines():
        if not line.strip():
            continue
        o = json.loads(line)
        o["layers"] = [(base64.b64decode(k), base64.b64decode(v))
                       for k, v in o["layers"]]
        records.append(o)
    return records


class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle_tpu_serving/1.0"
    # chunked transfer-encoding (streaming) requires HTTP/1.1; every
    # non-stream reply carries Content-Length so keep-alive stays valid
    protocol_version = "HTTP/1.1"

    @property
    def _srv(self):
        return self.server._serving_server  # type: ignore[attr-defined]

    def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        path = self.path.split("?", 1)[0]
        if path in ("/kv/export", "/kv/ingest"):
            self._kv_transfer(path)
            return
        if path != "/generate":
            self._reply(404, {"error": "not found"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length) or b"{}")
            prompt = body.get("prompt")
            if (not isinstance(prompt, list) or not prompt
                    or not all(isinstance(t, int) for t in prompt)):
                self._reply(400, {"error": "prompt must be a non-empty "
                                           "list of token ids"})
                return
            stream = bool(body.get("stream", False))
            req = self._srv.engine.submit(
                prompt,
                max_new_tokens=int(body.get("max_new_tokens", 16)),
                temperature=float(body.get("temperature", 0.0)),
                eos_token_id=body.get("eos_token_id"),
                tier=str(body.get("tier", "default")),
                prefill_only=bool(body.get("prefill_only", False)))
        except QueueFullError as e:
            # honest load shedding: tell the client WHEN to come back
            # instead of queueing without bound or failing opaquely
            self._reply(503, {"error": str(e),
                              "queue_depth": e.depth,
                              "queue_limit": e.limit,
                              "retry_after_s": e.retry_after_s},
                        headers={"Retry-After":
                                 str(max(1, int(round(e.retry_after_s))))})
            return
        except ValueError as e:
            self._reply(400, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001 — malformed JSON etc.
            self._reply(400, {"error": f"{type(e).__name__}: {e}"})
            return
        timeout = float(get_flag("serving_request_timeout_s"))
        if stream:
            self._stream(req, timeout)
            return
        if not req.wait(timeout):
            # evict the abandoned request so its slot and worst-case KV
            # reservation go back to the pool instead of decoding for a
            # client that already gave up
            cancelled = self._srv.engine.cancel(req, reason="timeout")
            self._reply(504, {"error": "generation timed out",
                              "request_id": req.request_id,
                              "cancelled": cancelled})
            return
        self._reply(200, {
            "request_id": req.request_id,
            "output_tokens": req.output_tokens,
            "finish_reason": req.finish_reason,
            "telemetry": req.telemetry(),
        })

    def _kv_transfer(self, path: str) -> None:
        """Block-transfer wire for disaggregated serving / live migration.

          POST /kv/export  {"tokens": [int, ...]}
                       ->  NDJSON, one line per RESIDENT full prompt
                           block (chain order, base64 page payloads)
          POST /kv/ingest  that NDJSON body
                       ->  {"imported", "dedup", "rejected", "skipped",
                            "bytes"} — chain-hash verified, idempotent
        """
        try:
            length = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(length)
            if path == "/kv/export":
                body = json.loads(raw or b"{}")
                tokens = body.get("tokens")
                if (not isinstance(tokens, list)
                        or not all(isinstance(t, int) for t in tokens)):
                    self._reply(400, {"error": "tokens must be a list of "
                                               "token ids"})
                    return
                recs = self._srv.engine.export_kv_blocks(tokens)
                self._reply_raw(200, kv_wire_encode(recs),
                                "application/x-ndjson")
            else:
                stats = self._srv.engine.ingest_kv_blocks(
                    kv_wire_decode(raw))
                self._reply(200, stats)
        except Exception as e:  # noqa: BLE001 — malformed payloads etc.
            self._reply(400, {"error": f"{type(e).__name__}: {e}"})

    def _stream(self, req, timeout: float) -> None:
        """Chunked NDJSON: one line per engine flush with the newly
        materialized tokens, a final line with the finish reason and
        telemetry. The engine pulses req's progress event at every
        deferred-fetch flush; snapshots are taken under the engine lock so
        a line never shows tokens past an eos truncation. A broken pipe
        (client gone) cancels the request so it stops consuming slots."""
        import time as _time

        engine = self._srv.engine
        deadline = _time.monotonic() + timeout
        sent = 0
        try:
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            while True:
                req._progress.clear()
                toks, state, reason = engine.snapshot_output(req)
                if len(toks) > sent:
                    self._chunk({"request_id": req.request_id,
                                 "tokens": toks[sent:], "done": False})
                    sent = len(toks)
                if state == "finished":
                    self._chunk({"request_id": req.request_id,
                                 "done": True, "finish_reason": reason,
                                 "telemetry": req.telemetry()})
                    break
                if _time.monotonic() > deadline:
                    engine.cancel(req, reason="timeout")
                    self._chunk({"request_id": req.request_id,
                                 "done": True, "finish_reason": "timeout"})
                    break
                req.wait_progress(timeout=0.25)
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            engine.cancel(req, reason="disconnect")

    def _chunk(self, obj) -> None:
        line = json.dumps(obj).encode() + b"\n"
        self.wfile.write(f"{len(line):x}\r\n".encode() + line + b"\r\n")
        self.wfile.flush()

    def do_GET(self):  # noqa: N802
        path = self.path.split("?", 1)[0]
        if path == "/stats":
            # one lock acquisition inside stats(): the whole snapshot is
            # consistent even while streaming requests mutate the
            # scheduler between ticks
            self._reply(200, self._srv.engine.stats())
        elif path == "/metrics":
            self._reply_raw(200, _obs_serve.metrics_body(),
                            "text/plain; version=0.0.4; charset=utf-8")
        elif path in ("/healthz", "/health"):
            snap = self._srv.engine.obs.health_snapshot(
                loop_alive=self._srv.loop_alive())
            self._reply(200 if snap["ok"] else 503, snap)
        else:
            self._reply(404, {"error": "not found"})

    def _reply(self, code: int, obj, headers=None) -> None:
        self._reply_raw(code, json.dumps(obj).encode(), "application/json",
                        headers=headers)

    def _reply_raw(self, code: int, body: bytes, ctype: str,
                   headers=None) -> None:
        try:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def log_message(self, fmt, *args):  # requests must not spam stderr
        pass


class _FleetHandler(_Handler):
    """Fleet front end: same wire protocol as _Handler, but requests are
    routed across N replicas by a FleetRouter — replica death, hedging
    and drains are invisible to the client beyond the telemetry block.

      POST /generate   as _Handler (no streaming: a fleet request may
                       migrate replicas mid-flight, so tokens are only
                       final once the request settles)
      POST /drain      {"replica": "replica-0"} — rolling-restart drain;
                       /resume undoes it
      GET  /healthz    200 while ANY replica can take traffic; body
                       carries every replica's own health snapshot
                       (including `draining`) + breaker state
      GET  /stats      router + per-replica engine snapshots
    """

    @property
    def _router(self):
        return self._srv.router  # type: ignore[attr-defined]

    def do_POST(self):  # noqa: N802
        path = self.path.split("?", 1)[0]
        if path in ("/drain", "/resume"):
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                rid = str(body.get("replica", ""))
                if rid not in self._router.replicas:
                    self._reply(404, {"error": f"unknown replica {rid!r}"})
                    return
                if path == "/drain":
                    self._router.drain(rid)
                    self._reply(200, {"replica": rid, "status": "draining",
                                      "drained": self._router.drained(rid)})
                else:
                    self._router.resume(rid)
                    self._reply(200, {"replica": rid, "status": "ok"})
            except Exception as e:  # noqa: BLE001 — malformed JSON etc.
                self._reply(400, {"error": f"{type(e).__name__}: {e}"})
            return
        if path != "/generate":
            self._reply(404, {"error": "not found"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length) or b"{}")
            prompt = body.get("prompt")
            if (not isinstance(prompt, list) or not prompt
                    or not all(isinstance(t, int) for t in prompt)):
                self._reply(400, {"error": "prompt must be a non-empty "
                                           "list of token ids"})
                return
            freq = self._router.submit(
                prompt,
                max_new_tokens=int(body.get("max_new_tokens", 16)),
                temperature=float(body.get("temperature", 0.0)),
                eos_token_id=body.get("eos_token_id"),
                tier=str(body.get("tier", "default")))
        except QueueFullError as e:
            self._reply(503, {"error": str(e),
                              "queue_depth": e.depth,
                              "queue_limit": e.limit,
                              "retry_after_s": e.retry_after_s},
                        headers={"Retry-After":
                                 str(max(1, int(round(e.retry_after_s))))})
            return
        except ValueError as e:
            self._reply(400, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001 — malformed JSON etc.
            self._reply(400, {"error": f"{type(e).__name__}: {e}"})
            return
        timeout = float(get_flag("serving_request_timeout_s"))
        if not freq.wait(timeout):
            self._reply(504, {"error": "generation timed out",
                              "request_id": freq.request_id})
            return
        self._reply(200, {
            "request_id": freq.request_id,
            "output_tokens": freq.output_tokens,
            "finish_reason": freq.finish_reason,
            "fleet": {"redispatches": freq.redispatches,
                      "hedged": freq.hedged},
        })

    def do_GET(self):  # noqa: N802
        split = self.path.split("?", 1)
        path = split[0]
        if path == "/stats":
            self._reply(200, self._router.stats())
        elif path == "/metrics":
            # fleet_slo_seconds gauges are rollups over the attempt
            # histograms: recompute at scrape time so they are current
            self._router.obs.publish_rollups()
            self._reply_raw(200, _obs_serve.metrics_body(),
                            "text/plain; version=0.0.4; charset=utf-8")
        elif path in ("/healthz", "/health"):
            snap = self._router.health()
            self._reply(200 if snap["ok"] else 503, snap)
        elif path == "/trace":
            query = parse_qs(split[1]) if len(split) > 1 else {}
            rid = (query.get("id") or [None])[0]
            if not rid:
                self._reply(400, {"error": "usage: /trace?id=<request_id>"})
                return
            payload = self._router.obs.trace_payload(rid)
            if payload is None:
                self._reply(404, {
                    "error": f"no merged trace for request {rid!r} "
                             "(unknown id, evicted from the settled "
                             "ring, or FLAGS_metrics was off at submit)"})
                return
            self._reply(200, payload)
        else:
            self._reply(404, {"error": "not found"})


class FleetServer:
    """HTTP front end over a FleetRouter. The router owns the replica
    engine loops and the failure monitor; this server only binds the
    socket and starts/stops the router alongside it."""

    def __init__(self, router, port: Optional[int] = None,
                 host: str = "127.0.0.1"):
        self.router = router
        if port is None:
            port = int(get_flag("serving_port"))
        self._httpd = ThreadingHTTPServer((host, int(port)), _FleetHandler)
        self._httpd.daemon_threads = True
        self._httpd._serving_server = self  # type: ignore[attr-defined]
        self.port = int(self._httpd.server_address[1])
        self.host = host
        self.router.start()
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.25},
            name="fleet-http", daemon=True)
        self._http_thread.start()

    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._http_thread.join(timeout=5)
        self.router.stop()

    def __repr__(self):  # pragma: no cover
        return f"FleetServer(port={self.port})"


class ServingServer:
    """HTTP server + the engine loop thread. The loop runs engine ticks
    while there is work and idles (short sleep) otherwise; handler threads
    never touch the device."""

    def __init__(self, engine, port: Optional[int] = None,
                 host: str = "127.0.0.1", idle_sleep_s: float = 0.002):
        self.engine = engine
        if port is None:
            port = int(get_flag("serving_port"))
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._httpd._serving_server = self  # type: ignore[attr-defined]
        self.port = int(self._httpd.server_address[1])
        self.host = host
        self._idle_sleep_s = float(idle_sleep_s)
        # optional dedicated observability port (FLAGS_serving_metrics_
        # port, defined in serving/observability.py): the process-wide
        # /metrics + training-style /healthz via observability/serve.py.
        # Bind failure degrades to None — never a dead serving process.
        self.metrics_server = None
        mp = int(get_flag("serving_metrics_port"))
        if mp > 0:
            try:
                self.metrics_server = _obs_serve.MetricsServer(mp)
            except OSError:
                pass
        self._stop = threading.Event()
        self._loop = threading.Thread(target=self._run_loop,
                                      name="serving-engine", daemon=True)
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.25},
            name="serving-http", daemon=True)
        self._loop.start()
        self._http_thread.start()

    def _run_loop(self) -> None:
        while not self._stop.is_set():
            if self.engine.sched.has_work():
                self.engine.step()
            else:
                time.sleep(self._idle_sleep_s)

    def loop_alive(self) -> bool:
        return self._loop.is_alive()

    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        self._stop.set()
        self._loop.join(timeout=10)
        self._httpd.shutdown()
        self._httpd.server_close()
        self._http_thread.join(timeout=5)
        if self.metrics_server is not None:
            try:
                self.metrics_server.stop()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
            self.metrics_server = None

    def __repr__(self):  # pragma: no cover
        return f"ServingServer(port={self.port})"
