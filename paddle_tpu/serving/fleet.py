"""FleetRouter: fault-tolerant routing across N ServingEngine replicas.

One engine is one replica and one point of failure; the fleet layer puts
a router in front of N of them (replica loops as threads, discovery and
liveness through the process-group store — the same ranks-as-threads
trick the elastic trainer uses, so the shape carries to real processes
over a TCPStore unchanged):

  * prefix-cache-aware routing: the chain hashes in serving/blocks.py are
    content addresses, so the router asks each healthy replica how many
    prompt tokens its cache would serve (allocator.peek_match, no side
    effects) and routes to the longest matching chain, breaking ties by
    least load.
  * health: every replica loop heartbeats a store lease
    (ReplicaRegistry); a replica whose lease expires or whose loop thread
    died is DEAD. A consecutive-error circuit breaker (open -> half-open
    probe -> closed) takes a replica that keeps failing submissions or
    ticks out of rotation without waiting for the lease to lapse.
  * re-dispatch: requests in flight on a dead replica are resubmitted —
    same request id, full prompt — onto a survivor. Partial output is
    discarded; greedy decode is deterministic, so the re-dispatched
    output is bitwise-identical to a no-failure run.
  * hedged retries: a request stuck past a TTFT deadline on a live-but-
    slow replica is duplicated onto a second one; the first replica to
    produce a token wins and the loser is cancelled through
    ServingEngine.cancel(), freeing its slot and KV reservation.
  * graceful drain: drain(rid) stops admitting to one replica while its
    in-flight work completes (/healthz says `draining`) — rolling
    restarts without dropping a request.
  * load shedding: when every healthy replica's queue is full the router
    raises QueueFullError with a jittered Retry-After, so the shed wave
    does not come back in lockstep.
  * disaggregated prefill/decode: FLAGS_fleet_roles splits the fleet
    into prefill-heavy and decode-packed replicas. A request first runs
    prefill-only on a prefill replica; its finished FULL KV blocks
    stream to the best decode replica over the /kv wire (chain-hash
    keyed, idempotent — engine.export_kv_blocks/ingest_kv_blocks), and
    the decode attempt admits them as local prefix-cache hits. The
    default ("symmetric") keeps every replica dual-role: exactly
    today's behavior.
  * live KV migration: drain(rid, migrate=True) ships each in-flight
    session's resident prompt blocks to a survivor over the same wire
    and re-places the attempt there — the survivor re-decodes (greedy:
    bitwise identical) without re-prefilling any already-full block.
  * elastic autoscaling: FleetAutoscaler tracks offered load against
    fleet capacity and spawns (add_replica + the r20 warm-up gate) or
    retires (migration-assisted drain, then remove_replica) replicas
    under hysteresis thresholds and a cooldown.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

from ..core import flags as _flags
from ..distributed.env import InProcStore, ReplicaRegistry
from ..observability import spans as _spans
from ..observability.registry import counter as _counter
from ..observability.registry import gauge as _gauge
from ..observability.registry import histogram as _histogram
from . import fleet_observability as _fobs
from .engine import EngineDrainingError, QueueFullError, ServingEngine
from .observability import RequestTrace

_flags.define_flag("fleet_replicas", 2,
                   "Serving replicas a fleet front end builds when not "
                   "given explicit engines (tools/servebench.py fleet "
                   "mode; FleetServer).")
_flags.define_flag("fleet_hedge_ttft_ms", 0.0,
                   "Hedged-retry TTFT deadline in milliseconds: a request "
                   "with no first token past this age is duplicated onto "
                   "a second healthy replica; first token wins and the "
                   "loser is cancelled (slot + KV reservation freed). "
                   "0 (default) disables hedging.")
_flags.define_flag("fleet_breaker_errors", 3,
                   "Consecutive submission/tick errors that open a "
                   "replica's circuit breaker (replica leaves the routing "
                   "set until a half-open probe succeeds).")
_flags.define_flag("fleet_breaker_cooldown_s", 2.0,
                   "Seconds an open circuit breaker waits before allowing "
                   "one half-open probe request through.")
_flags.define_flag("fleet_roles", "symmetric",
                   "Replica role layout for disaggregated serving: "
                   "'symmetric' (default — every replica both prefils and "
                   "decodes, exactly the pre-disagg behavior) or a "
                   "'role:count,...' spec like 'prefill:1,decode:3' "
                   "assigned to replicas in construction order. Prefill "
                   "replicas only run prefill-only attempts and stream "
                   "their finished KV blocks; decode replicas only host "
                   "decode attempts.")
_flags.define_flag("fleet_drain_migrate", False,
                   "When on, drain(rid) also live-migrates in-flight "
                   "sessions: their resident prompt KV blocks stream to a "
                   "survivor and the attempts re-place there instead of "
                   "finishing on the draining replica. Off keeps the r18 "
                   "drain semantics (in-flight work completes in place).")
_flags.define_flag("fleet_scale_min", 1,
                   "FleetAutoscaler floor: scalable replicas are never "
                   "drained below this count.")
_flags.define_flag("fleet_scale_max", 8,
                   "FleetAutoscaler ceiling: never spawn past this many "
                   "scalable replicas.")
_flags.define_flag("fleet_scale_hi", 0.85,
                   "Scale-up threshold: utilization (offered load / fleet "
                   "slot capacity) at or above this spawns a replica once "
                   "the cooldown allows.")
_flags.define_flag("fleet_scale_lo", 0.25,
                   "Scale-down threshold: utilization at or below this "
                   "drains (migration-assisted) and retires the least "
                   "loaded scalable replica.")
_flags.define_flag("fleet_scale_cooldown_s", 5.0,
                   "Minimum seconds between autoscaler actions, so a "
                   "bursty curve cannot flap the fleet.")

# fleet-level SLO + routing telemetry: always-on like the engine's tier
# histograms. The engine-level serving_* histograms are registry-global,
# so they already aggregate across every replica in the process; the
# fleet_* ones below measure the REQUEST as the client saw it (arrival at
# the router to first token / finish, across re-dispatches and hedges).
_ROUTED = _counter("fleet_requests_routed_total",
                   "Requests dispatched to a replica (first placement).",
                   labelnames=("replica",), always=True)
_REDISPATCHED = _counter("fleet_requests_redispatched_total",
                         "In-flight requests resubmitted to a survivor "
                         "after their replica died.", always=True)
_HEDGED = _counter("fleet_requests_hedged_total",
                   "Requests duplicated onto a second replica past the "
                   "TTFT hedge deadline.", always=True)
_HEDGE_WINS = _counter("fleet_hedge_wins_total",
                       "Hedged requests resolved, by which attempt "
                       "produced the first token.",
                       labelnames=("winner",), always=True)
_FLEET_SHED = _counter("fleet_requests_shed_total",
                       "Requests rejected fleet-wide (503 + Retry-After).",
                       labelnames=("reason",), always=True)
_REPLICA_UP = _gauge("fleet_replica_health",
                     "Routable health per replica: 1 healthy, 0.5 "
                     "draining, 0.25 breaker open, 0 dead.",
                     labelnames=("replica",), always=True)
_FLEET_TTFT = _histogram("fleet_ttft_seconds",
                         "Router arrival to first token, across "
                         "re-dispatches and hedges.",
                         labelnames=("tier",), always=True)
_FLEET_E2E = _histogram("fleet_e2e_seconds",
                        "Router arrival to finish, across re-dispatches "
                        "and hedges.", labelnames=("tier",), always=True)

_GOOD_REASONS = ("stop", "length")

_fleet_req_lock = threading.Lock()
_fleet_req_counter = 0


def _next_fleet_id() -> str:
    global _fleet_req_counter
    with _fleet_req_lock:
        _fleet_req_counter += 1
        return f"fleet-{_fleet_req_counter}"


_ROLES = ("prefill", "decode", "any")


def parse_fleet_roles(spec: Optional[str], n_replicas: int) -> List[str]:
    """Expand a FLAGS_fleet_roles spec to one role per replica, in
    construction order. 'symmetric' / empty -> all 'any' (the pre-disagg
    behavior); otherwise 'role:count,...' must cover every replica."""
    spec = (spec or "symmetric").strip().lower()
    if spec in ("", "symmetric"):
        return ["any"] * n_replicas
    roles: List[str] = []
    for part in spec.split(","):
        name, _, count = part.partition(":")
        name = name.strip()
        if name not in _ROLES:
            raise ValueError(f"unknown fleet role {name!r} "
                             f"(want one of {_ROLES})")
        roles.extend([name] * int(count or 1))
    if len(roles) != n_replicas:
        raise ValueError(f"fleet_roles covers {len(roles)} replicas, "
                         f"fleet has {n_replicas}")
    return roles


class CircuitBreaker:
    """Consecutive-error breaker: closed -> open after `max_errors`
    failures in a row -> half-open after `cooldown_s` (ONE probe allowed
    through) -> closed on probe success, re-open on probe failure."""

    def __init__(self, max_errors: int, cooldown_s: float,
                 clock=time.monotonic):
        self.max_errors = int(max_errors)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._errors = 0
        self._opened_at: Optional[float] = None
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def _state_locked(self) -> str:
        if self._opened_at is None:
            return "closed"
        if self._clock() - self._opened_at >= self.cooldown_s:
            return "half_open"
        return "open"

    def allow(self) -> bool:
        """May a request be sent through right now? In half-open exactly
        one caller wins the probe token; the rest stay rejected until the
        probe resolves via record_success/record_failure."""
        with self._lock:
            st = self._state_locked()
            if st == "closed":
                return True
            if st == "half_open" and not self._probing:
                self._probing = True
                return True
            return False

    def record_success(self):
        with self._lock:
            self._errors = 0
            self._opened_at = None
            self._probing = False

    def record_failure(self):
        with self._lock:
            self._errors += 1
            if self._probing or self._errors >= self.max_errors:
                self._opened_at = self._clock()
                self._probing = False


class _Attempt:
    """One engine-level placement of a fleet request."""
    __slots__ = ("replica", "req", "kind", "failed", "index", "route_t0")

    def __init__(self, replica: "Replica", req, kind: str,
                 index: int = 0, route_t0: Optional[float] = None):
        self.replica = replica
        self.req = req
        self.kind = kind            # "primary" | "redispatch" | "hedge"
        self.failed = False
        self.index = int(index)     # position in FleetRequest.attempts
        self.route_t0 = route_t0    # monotonic s at routing-decision entry


class FleetRequest:
    """Router-level request handle: survives replica death (the engine
    request it maps to may be replaced by a re-dispatch or raced by a
    hedge; callers only ever see this object)."""

    def __init__(self, prompt: List[int], *, max_new_tokens: int,
                 temperature: float, eos_token_id: Optional[int],
                 request_id: Optional[str], tier: str, router: "FleetRouter",
                 submit_ts: float):
        self.prompt = list(prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.eos_token_id = eos_token_id
        self.request_id = request_id or _next_fleet_id()
        self.tier = tier
        self.submit_ts = submit_ts
        self.first_token_ts: Optional[float] = None
        self.finish_ts: Optional[float] = None
        self.output_tokens: List[int] = []
        self.finish_reason: Optional[str] = None
        self.attempts: List[_Attempt] = []
        self.hedged = False
        self.redispatches = 0
        # disaggregation bookkeeping: the last KV-block transfer this
        # request rode ({src, dst, imported, dedup, ...}) and how many
        # times it was live-migrated off a draining replica
        self.kv_streamed: Optional[dict] = None
        self.migrations = 0
        # router-lane RequestTrace (route decisions, queue-at-router,
        # hedge fire/win/cancel); None when spans were off at submit
        self.trace: Optional[RequestTrace] = None
        self._orphan_ns: Optional[int] = None  # orphan-detection instant
        self._router = router
        self._lock = threading.Lock()
        self._settled = False
        self._done = threading.Event()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def live_attempts(self) -> List[_Attempt]:
        with self._lock:
            return [a for a in self.attempts if not a.failed]

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the request finishes (on ANY replica). Driven by
        the engine-level done events of the current attempts, with the
        router's settle logic run from the waiter's thread — completion
        does not wait for the monitor tick."""
        deadline = (None if timeout is None
                    else time.monotonic() + float(timeout))
        while True:
            if self._done.is_set():
                return True
            self._router._settle(self)
            if self._done.is_set():
                return True
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            if remaining is not None and remaining <= 0:
                return False
            slice_s = 0.05 if remaining is None else min(0.05, remaining)
            atts = self.live_attempts()
            if atts:
                atts[0].req.wait(slice_s)
            else:
                # between death and re-dispatch: nothing to wait on
                time.sleep(min(slice_s, 0.005))


class Replica:
    """One ServingEngine plus its loop thread, heartbeat lease, breaker,
    and drain flag. kill() simulates a crash (loop exits, heartbeats
    stop, nothing cleaned up); pause() simulates a hang (loop alive and
    heartbeating but not stepping — the hedging target).

    Subclasses with real isolation (serving/fleet_proc.ProcessReplica)
    override the lifecycle + liveness surface: dead(), warming(),
    supervise() and the routing probes. The router only ever talks to
    this interface, so in-proc threads and supervised OS processes ride
    the same `_place()` path."""

    def __init__(self, rid: str, engine: ServingEngine, *,
                 registry: ReplicaRegistry, heartbeat_s: float,
                 breaker: CircuitBreaker, clock=time.monotonic,
                 idle_sleep_s: float = 0.002):
        self.rid = rid
        self.engine = engine
        self.registry = registry
        self.heartbeat_s = float(heartbeat_s)
        self.breaker = breaker
        self.draining = False
        # disaggregation role: "any" (dual: the symmetric default),
        # "prefill" (prefill-only attempts; KV streams out), "decode"
        # (decode attempts only; KV streams in)
        self.role = "any"
        # supervision surface (constant for thread replicas; live for
        # process replicas): incarnation fence, host pid, respawn count,
        # last exit record {incarnation, pid, exit_code, reason, ...}
        self.incarnation = 0
        self.pid: Optional[int] = os.getpid()
        self.respawns = 0
        self.last_exit: Optional[dict] = None
        self._clock = clock
        self._idle_sleep_s = float(idle_sleep_s)
        self._stop = threading.Event()
        self._pause = threading.Event()
        self._killed = False
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        self.registry.heartbeat(self.rid)
        self._thread = threading.Thread(
            target=self._loop, name=f"fleet-{self.rid}", daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    def kill(self):
        """Simulated crash: the loop exits without any cleanup and the
        heartbeat lease is left to expire."""
        self._killed = True
        self._stop.set()

    def pause(self):
        self._pause.set()

    def unpause(self):
        self._pause.clear()

    def loop_alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- liveness / supervision (overridden by ProcessReplica) -------------
    def dead(self, lease_ttl_s: float) -> bool:
        """Is this replica dead right now? Thread replicas die when
        killed, when their loop thread exited, or when their store lease
        lapsed."""
        if self._killed:
            return True
        if self._thread is not None and not self._thread.is_alive():
            return True
        return not self.registry.alive(self.rid, float(lease_ttl_s))

    def warming(self) -> bool:
        """True while the replica exists but must not take traffic yet
        (a respawned process incarnation before its warm-up probe)."""
        return False

    def supervise(self, router: "FleetRouter") -> None:
        """One supervision turn, called from every router poll. Thread
        replicas have no supervisor (a dead thread stays dead); process
        replicas detect death, run the backoff/fence/respawn state
        machine here."""

    def _loop(self):
        hb_last = -float("inf")
        while not self._stop.is_set():
            now = self._clock()
            if now - hb_last >= self.heartbeat_s:
                self.registry.heartbeat(self.rid)
                hb_last = now
            if self._pause.is_set():
                time.sleep(self._idle_sleep_s)
                continue
            try:
                if self.engine.sched.has_work():
                    self.engine.step()
                    self.breaker.record_success()
                else:
                    time.sleep(self._idle_sleep_s)
            except Exception:  # noqa: BLE001 — a tick fault is a breaker
                self.breaker.record_failure()  # strike, not a loop crash
                time.sleep(self._idle_sleep_s)

    # -- routing inputs ----------------------------------------------------
    def load(self) -> int:
        s = self.engine.sched
        return len(s.waiting) + len(s.prefilling) + len(s.running)

    def affinity(self, prompt: List[int]) -> int:
        """Prompt tokens this replica's cache would serve (content-
        addressed chain match; consistent read under the engine lock)."""
        if not self.engine.prefix_cache:
            return 0
        with self.engine._lock:
            return int(self.engine.allocator.peek_match(prompt))

    def queue_depth(self) -> int:
        return len(self.engine.sched.waiting)


class FleetRouter:
    """Routes requests across replicas; detects failures via store
    heartbeat leases + circuit breakers; re-dispatches, hedges, drains
    and sheds. Replica engine loops and the monitor are daemon threads
    owned by the router (start()/stop())."""

    def __init__(self, engines: Optional[List[ServingEngine]] = None, *,
                 replica_specs: Optional[List] = None,
                 store=None, prefix: str = "/pt/fleet",
                 roles: Optional[str] = None,
                 hedge_ttft_ms: Optional[float] = None,
                 breaker_errors: Optional[int] = None,
                 breaker_cooldown_s: Optional[float] = None,
                 heartbeat_s: float = 0.05, lease_ttl_s: float = 0.5,
                 poll_interval_s: float = 0.02,
                 idle_sleep_s: float = 0.002, clock=time.monotonic):
        engines = list(engines or [])
        replica_specs = list(replica_specs or [])
        if not engines and not replica_specs:
            raise ValueError("FleetRouter needs at least one engine or "
                             "replica spec")
        self._clock = clock
        self.lease_ttl_s = float(lease_ttl_s)
        self.poll_interval_s = float(poll_interval_s)
        self._heartbeat_s = float(heartbeat_s)
        self._idle_sleep_s = float(idle_sleep_s)
        self.hedge_ttft_s = float(
            _flags.get_flag("fleet_hedge_ttft_ms")
            if hedge_ttft_ms is None else hedge_ttft_ms) / 1000.0
        max_errors = int(_flags.get_flag("fleet_breaker_errors")
                         if breaker_errors is None else breaker_errors)
        cooldown = float(_flags.get_flag("fleet_breaker_cooldown_s")
                         if breaker_cooldown_s is None else
                         breaker_cooldown_s)
        self._breaker_cfg = (max_errors, cooldown)
        self.registry = ReplicaRegistry(store if store is not None
                                        else InProcStore(),
                                        prefix=prefix, clock=clock)
        self.replicas: Dict[str, Replica] = {}
        for i, eng in enumerate(engines):
            rid = f"replica-{i}"
            rep = Replica(rid, eng, registry=self.registry,
                          heartbeat_s=heartbeat_s,
                          breaker=CircuitBreaker(max_errors, cooldown,
                                                 clock=clock),
                          clock=clock, idle_sleep_s=idle_sleep_s)
            self.replicas[rid] = rep
            self.registry.register(rid, meta={
                "slots": eng.max_slots, "blocks": eng.num_blocks})
        # process-isolated replicas: each spec builds a Replica subclass
        # (serving/fleet_proc.ProcessReplicaSpec -> ProcessReplica) that
        # rides the same _place()/poll() path as the thread replicas
        for j, spec in enumerate(replica_specs):
            rid = f"replica-{len(engines) + j}"
            rep = spec.build(rid, registry=self.registry,
                             heartbeat_s=heartbeat_s,
                             breaker=CircuitBreaker(max_errors, cooldown,
                                                    clock=clock),
                             clock=clock, idle_sleep_s=idle_sleep_s)
            self.replicas[rid] = rep
            self.registry.register(rid, meta={"kind": "process"})
        role_spec = (str(_flags.get_flag("fleet_roles"))
                     if roles is None else roles)
        for rep, role in zip(self.replicas.values(),
                             parse_fleet_roles(role_spec,
                                               len(self.replicas))):
            rep.role = role
        self._next_rid = len(self.replicas)
        self._started = False
        self.autoscaler = None          # attach_autoscaler() ticks in poll
        self._inflight: Dict[str, FleetRequest] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        # fleet observability hub: trace merge, attempt SLOs, anomaly
        # detectors + flight dumps (serving/fleet_observability.py)
        self.obs = _fobs.FleetObservability(self)
        # last breaker state seen per replica, to turn the breakers'
        # implicit (time-derived) transitions into explicit events
        self._breaker_seen: Dict[str, str] = {
            rid: "closed" for rid in self.replicas}

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self._started = True
        for rep in list(self.replicas.values()):
            rep.start()
        if self._monitor is None:
            self._monitor = threading.Thread(
                target=self._monitor_loop, name="fleet-monitor", daemon=True)
            self._monitor.start()
        return self

    def stop(self):
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=10.0)
            self._monitor = None
        for rep in list(self.replicas.values()):
            rep.stop()

    def _monitor_loop(self):
        while not self._stop.is_set():
            try:
                self.poll()
            except Exception:  # noqa: BLE001 — the monitor must survive
                pass
            time.sleep(self.poll_interval_s)

    # -- health ------------------------------------------------------------
    def replica_dead(self, rep: Replica) -> bool:
        return rep.dead(self.lease_ttl_s)

    def routable(self, rep: Replica) -> bool:
        """May NEW work be placed on this replica right now? (Breaker
        half-open counts: allow() hands out the probe token at submit.)"""
        return (not self.replica_dead(rep) and not rep.draining
                and not rep.warming() and rep.breaker.state != "open")

    def _breaker_event(self, rep: Replica):
        """Surface a breaker state change as an observability event.
        Called after every record_success/record_failure on the router
        path and once per poll per replica (the engine loop strikes the
        breaker from its own thread, and open -> half_open is
        time-derived, so poll-time sampling catches both)."""
        new = rep.breaker.state
        old = self._breaker_seen.get(rep.rid)
        if new != old:
            self._breaker_seen[rep.rid] = new
            self.obs.on_breaker(rep.rid, old, new)

    def _refresh_health_gauges(self):
        for rep in self.replicas.values():
            self._breaker_event(rep)
            if self.replica_dead(rep):
                v = 0.0
            elif rep.draining:
                v = 0.5
            elif rep.breaker.state == "open":
                v = 0.25
            else:
                v = 1.0
            _REPLICA_UP.set(v, replica=rep.rid)

    # -- admission / routing -----------------------------------------------
    def _ranked(self, prompt: List[int],
                exclude: Optional[set] = None) -> List[Replica]:
        """Healthy replicas, best first: longest cached prefix chain,
        then least load, then stable id order."""
        scored = []
        for rep in self.replicas.values():
            if exclude and rep.rid in exclude:
                continue
            if not self.routable(rep):
                continue
            scored.append((-rep.affinity(prompt), rep.load(), rep.rid, rep))
        scored.sort(key=lambda t: t[:3])
        return [t[3] for t in scored]

    def _role_ok(self, rep: Replica, cause: str) -> bool:
        """May a `cause` attempt land on this replica's role? Prefill-only
        attempts go to prefill replicas, everything else to decode ones;
        'any' (the symmetric default) hosts both."""
        if cause == "prefill":
            return rep.role in ("prefill", "any")
        return rep.role in ("decode", "any")

    def _place(self, freq: FleetRequest, cause: str,
               exclude: Optional[set] = None,
               prefer: Optional[str] = None):
        """Place ONE attempt of `freq` on the best healthy replica —
        the single routing path behind primary submit, re-dispatch,
        hedge, disaggregated prefill/decode and migration. Probes every
        role-compatible candidate (affinity + load; `prefer` pins a
        replica to the front, e.g. the KV-transfer target), stamps the
        engine placement with the distributed trace context
        ``{fleet_request_id, attempt, cause}``, and records the
        route-decision span (probe results included) through the fleet
        observability hub. A ``cause="prefill"`` placement submits
        prefill-only: the engine computes + keeps the prompt KV and
        finishes with "prefill_complete" instead of decoding. Returns
        ``(attempt, saw_queue_full)`` with ``attempt is None`` when no
        replica accepted."""
        t0_ns = time.monotonic_ns()
        probes = []
        scored = []
        for rep in self.replicas.values():
            if exclude and rep.rid in exclude:
                continue
            if not self.routable(rep) or not self._role_ok(rep, cause):
                continue
            aff = rep.affinity(freq.prompt)
            load = rep.load()
            probes.append({"replica": rep.rid, "affinity": int(aff),
                           "load": int(load)})
            scored.append((0 if rep.rid == prefer else 1, -aff, load,
                           rep.rid, rep))
        scored.sort(key=lambda t: t[:4])
        saw_queue_full = None
        for *_key, rep in scored:
            if not rep.breaker.allow():
                continue
            idx = len(freq.attempts)
            extra_kw = {"prefill_only": True} if cause == "prefill" else {}
            try:
                req = rep.engine.submit(
                    freq.prompt, max_new_tokens=freq.max_new_tokens,
                    temperature=freq.temperature,
                    eos_token_id=freq.eos_token_id,
                    request_id=freq.request_id, tier=freq.tier,
                    trace_ctx=_fobs.trace_context(freq.request_id, idx,
                                                  cause),
                    **extra_kw)
            except QueueFullError as e:
                # load, not fault: no breaker strike
                rep.breaker.record_success()
                self._breaker_event(rep)
                saw_queue_full = e
                continue
            except EngineDrainingError:
                rep.breaker.record_success()
                self._breaker_event(rep)
                continue
            except ValueError:
                raise                   # bad request, not a replica fault
            except Exception:  # noqa: BLE001 — replica fault
                rep.breaker.record_failure()
                self._breaker_event(rep)
                continue
            rep.breaker.record_success()
            self._breaker_event(rep)
            att = _Attempt(rep, req, cause, index=idx,
                           route_t0=t0_ns / 1e9)
            with freq._lock:
                freq.attempts.append(att)
            self.obs.on_dispatch(freq, att, probes, t0_ns)
            freq._orphan_ns = None
            _ROUTED.inc(replica=rep.rid)
            return att, saw_queue_full
        return None, saw_queue_full

    def submit(self, prompt: List[int], max_new_tokens: int = 16,
               temperature: float = 0.0,
               eos_token_id: Optional[int] = None,
               request_id: Optional[str] = None,
               tier: str = "default") -> FleetRequest:
        """Route a request to the best healthy replica. Raises
        QueueFullError (with a jittered Retry-After) when every healthy
        replica's queue is full — fleet-level load shedding."""
        freq = FleetRequest(prompt, max_new_tokens=max_new_tokens,
                            temperature=temperature,
                            eos_token_id=eos_token_id,
                            request_id=request_id, tier=tier, router=self,
                            submit_ts=self._clock())
        if _spans.enabled():
            freq.trace = RequestTrace(freq.request_id, freq.tier)
        att = saw_queue_full = None
        if self._disagg_active():
            # stage 1 of the disaggregated pipeline: prefill-only on a
            # prefill replica. _settle() advances the request to the KV
            # transfer + decode placement when it finishes. Falls through
            # to a direct decode placement when no prefill replica can
            # take it (all dead/full) — disagg degrades, never rejects.
            att, saw_queue_full = self._place(freq, "prefill")
        if att is None:
            att, saw_queue_full = self._place(freq, "primary")
        if att is None:
            if saw_queue_full is not None:
                _FLEET_SHED.inc(reason="queue_full")
                raise QueueFullError(saw_queue_full.depth,
                                     saw_queue_full.limit)
            _FLEET_SHED.inc(reason="no_healthy_replica")
            raise QueueFullError(0, 0)
        with self._lock:
            self._inflight[freq.request_id] = freq
        return freq

    # -- monitor pass (public so tests can drive it deterministically) -----
    def poll(self):
        """One supervision pass: refresh health, run each replica's
        supervisor turn (death detection / respawn state machine for
        process replicas; no-op for threads), settle finished requests,
        re-dispatch orphans of dead replicas, resolve and fire hedges."""
        for rep in list(self.replicas.values()):
            try:
                rep.supervise(self)
            except Exception:  # noqa: BLE001 — supervision must survive
                pass
        self._refresh_health_gauges()
        if self.autoscaler is not None:
            try:
                self.autoscaler.tick()
            except Exception:  # noqa: BLE001 — scaling must not wound poll
                pass
        now = self._clock()
        with self._lock:
            pending = list(self._inflight.values())
        for freq in pending:
            if self._settle(freq):
                continue
            self._redispatch_if_orphaned(freq)
            self._resolve_hedge(freq)
            self._maybe_hedge(freq, now)
        self.obs.tick()

    def _settle(self, freq: FleetRequest) -> bool:
        """Complete the fleet request if any attempt finished cleanly;
        cancel the losers. Returns True when the request is done. A
        finished prefill-only attempt never wins: it advances the
        disaggregated pipeline (KV stream + decode placement) instead."""
        advance = None
        with freq._lock:
            if freq._settled:
                return True
            winner = None
            for att in freq.attempts:
                if att.failed:
                    continue
                toks, state, reason = \
                    att.replica.engine.snapshot_output(att.req)
                if state == "finished":
                    if att.kind == "prefill":
                        # consumed either way: on prefill_complete the KV
                        # streams to a decode replica; on anything else
                        # (cancel, error) the decode placement below
                        # simply won't find streamed blocks
                        att.failed = True
                        advance = (att, reason)
                        continue
                    if reason in _GOOD_REASONS:
                        winner = (att, toks, reason)
                        break
                    att.failed = True    # cancelled out from under us
            if winner is None:
                if advance is None:
                    return False
            else:
                att, toks, reason = winner
                freq.output_tokens = list(toks)
                freq.finish_reason = reason
                if freq.first_token_ts is None \
                        and att.req.first_token_time is not None:
                    freq.first_token_ts = att.req.first_token_time
                freq.finish_ts = self._clock()
                losers = [a for a in freq.attempts
                          if a is not att and not a.failed]
                for a in losers:
                    a.failed = True
                if freq.hedged:
                    _HEDGE_WINS.inc(
                        winner="hedge" if att.kind == "hedge" else "primary")
                freq._settled = True
        if winner is None:
            self._advance_disagg(freq, advance[0], advance[1])
            return False
        for a in losers:
            toks_lost, _s, _r = a.replica.engine.snapshot_output(a.req)
            a.replica.engine.cancel(a.req, "hedge_lost")
            self.obs.on_cancelled(freq, a, len(toks_lost), "hedge_lost")
        if freq.hedged and losers:
            # hedge raced all the way to the finish (first token and
            # completion arrived in the same tick) — _resolve_hedge
            # never got to declare the winner
            self.obs.on_hedge_win(freq, att)
        if freq.first_token_ts is not None:
            _FLEET_TTFT.observe(max(0.0, freq.first_token_ts
                                    - freq.submit_ts), tier=freq.tier)
        _FLEET_E2E.observe(max(0.0, freq.finish_ts - freq.submit_ts),
                           tier=freq.tier)
        self.obs.on_settle(freq, att)
        with self._lock:
            self._inflight.pop(freq.request_id, None)
        freq._done.set()
        return True

    # -- disaggregated prefill/decode pipeline ------------------------------
    def _disagg_active(self) -> bool:
        """Run the two-stage pipeline only while a prefill replica can
        actually take work — otherwise requests place directly on the
        decode pool (full prefill there, symmetric behavior)."""
        return any(rep.role == "prefill" and self.routable(rep)
                   for rep in self.replicas.values())

    def _pick_decode_target(self, freq: FleetRequest,
                            exclude: Optional[set] = None
                            ) -> Optional[Replica]:
        """Best decode-capable replica for a KV transfer: longest cached
        chain (it may already hold the prefix), then least load."""
        scored = []
        for rep in self.replicas.values():
            if exclude and rep.rid in exclude:
                continue
            if not self.routable(rep) or not self._role_ok(rep, "decode"):
                continue
            scored.append((-rep.affinity(freq.prompt), rep.load(),
                           rep.rid, rep))
        scored.sort(key=lambda t: t[:3])
        return scored[0][3] if scored else None

    def _stream_kv(self, freq: FleetRequest, src: Replica,
                   dst: Replica, kind: str) -> Optional[dict]:
        """Ship `freq`'s resident prompt blocks src -> dst over the
        chain-hash wire. Best-effort: a failed transfer only costs the
        prefix hit (the decode replica re-prefils), never the request."""
        try:
            recs = src.engine.export_kv_blocks(freq.prompt)
            if not recs:
                return None
            stats = dst.engine.ingest_kv_blocks(recs)
        except Exception:  # noqa: BLE001 — replica died mid-transfer
            return None
        stats = dict(stats, src=src.rid, dst=dst.rid, kind=kind)
        freq.kv_streamed = stats
        self.obs.on_kv_transfer(freq, src.rid, dst.rid, stats, kind=kind)
        return stats

    def _advance_disagg(self, freq: FleetRequest, att: _Attempt,
                        reason: str) -> None:
        """Stage 2: the prefill-only attempt finished. Stream its KV
        blocks to the best decode replica, then place the decode attempt
        — preferring the transfer target, though affinity would find it
        anyway (the streamed chain IS the prefix-cache content the
        ranking probes). On a failed prefill (cancel/error) this is a
        plain decode placement: full prefill on the decode replica."""
        prefer = None
        if reason == "prefill_complete":
            target = self._pick_decode_target(freq,
                                              exclude={att.replica.rid})
            if target is not None:
                self._stream_kv(freq, att.replica, target, "prefill")
                prefer = target.rid
        att2, _ = self._place(freq, "decode", prefer=prefer)
        if att2 is None and freq._orphan_ns is None:
            # decode pool full/dead this pass: the next poll's orphan
            # re-dispatch keeps retrying — accepted requests never drop
            freq._orphan_ns = time.monotonic_ns()

    def _redispatch_if_orphaned(self, freq: FleetRequest):
        """Requests in flight on a dead replica are resubmitted (same id,
        full prompt) onto the best survivor; the dead attempt's partial
        output is discarded. Greedy decode is deterministic, so the
        survivor's output is bitwise what the dead replica would have
        produced."""
        dead = []
        with freq._lock:
            for att in freq.attempts:
                if not att.failed and self.replica_dead(att.replica):
                    att.failed = True
                    dead.append(att)
            tried = {a.replica.rid for a in freq.attempts}
            needs_new = not any(not a.failed for a in freq.attempts)
        for att in dead:
            # bookkeeping on the dead engine is still consistent (its
            # loop died, not the object): free the slot + reservation
            toks_lost = 0
            try:
                toks, _s, _r = att.replica.engine.snapshot_output(att.req)
                toks_lost = len(toks)
                att.replica.engine.cancel(att.req, "replica_dead")
            except Exception:  # noqa: BLE001 — dead replica, best effort
                pass
            self.obs.on_cancelled(freq, att, toks_lost, "replica_dead")
        if not needs_new:
            return
        if dead and freq._orphan_ns is None:
            # queue-at-router span anchor: orphan detected, not yet
            # re-placed (cleared by _place on success)
            freq._orphan_ns = time.monotonic_ns()
        # prefer a replica this request has not touched, but fall back
        # to retrying anywhere rather than dropping an accepted request
        fresh = any(self.routable(r) and r.rid not in tried
                    for r in self.replicas.values())
        att, _ = self._place(freq, "redispatch",
                             exclude=tried if fresh else None)
        if att is not None:
            with freq._lock:
                freq.redispatches += 1
            _REDISPATCHED.inc()
        # else: nowhere to go this pass (everyone full/dead) — the next
        # poll retries; accepted requests are never dropped

    def _resolve_hedge(self, freq: FleetRequest):
        """First token wins: as soon as exactly one live attempt has
        produced output, cancel the rest (don't wait for the finish)."""
        if not freq.hedged:
            return
        with freq._lock:
            live = [a for a in freq.attempts if not a.failed]
            if len(live) < 2:
                return
            holders = []
            for att in live:
                toks, _state, _reason = \
                    att.replica.engine.snapshot_output(att.req)
                if toks:
                    holders.append(att)
            if not holders:
                return
            winner = holders[0]
            if freq.first_token_ts is None \
                    and winner.req.first_token_time is not None:
                freq.first_token_ts = winner.req.first_token_time
            losers = [a for a in live if a is not winner]
            for a in losers:
                a.failed = True
        self.obs.on_hedge_win(freq, winner)
        for a in losers:
            toks_lost, _s, _r = a.replica.engine.snapshot_output(a.req)
            a.replica.engine.cancel(a.req, "hedge_lost")
            self.obs.on_cancelled(freq, a, len(toks_lost), "hedge_lost")

    def _maybe_hedge(self, freq: FleetRequest, now: float):
        if self.hedge_ttft_s <= 0 or freq.hedged:
            return
        if now - freq.submit_ts < self.hedge_ttft_s:
            return
        with freq._lock:
            live = [a for a in freq.attempts if not a.failed]
            hosting = {a.replica.rid for a in live}
        if any(a.kind == "prefill" for a in live):
            return          # still in the prefill stage: nothing to hedge
        for att in live:
            toks, _state, _reason = \
                att.replica.engine.snapshot_output(att.req)
            if toks:
                return                  # first token already arrived
        att, _ = self._place(freq, "hedge", exclude=hosting)
        if att is not None:
            with freq._lock:
                freq.hedged = True
            _HEDGED.inc()

    # -- drain / chaos -----------------------------------------------------
    def drain(self, rid: str, migrate: Optional[bool] = None):
        """Rolling-restart drain: stop routing to `rid`, stop its engine
        admitting. With `migrate` (default FLAGS_fleet_drain_migrate,
        off) in-flight sessions live-migrate to a survivor — their
        resident prompt KV blocks stream over the chain-hash wire and
        the attempts re-place there, so the survivor re-decodes (greedy:
        bitwise identical) without re-prefilling any already-full block.
        Without it they finish in place (the r18 semantics)."""
        with self._lock:
            rep = self.replicas[rid]
            rep.draining = True
            rep.engine.drain()
        if (bool(_flags.get_flag("fleet_drain_migrate"))
                if migrate is None else bool(migrate)):
            self.migrate_from(rid)

    def migrate_from(self, rid: str) -> int:
        """Live KV migration: for every in-flight attempt on `rid`, ship
        the session's resident prompt blocks to the best survivor,
        cancel the attempt locally and re-place it pinned to the
        survivor. Returns how many attempts moved; sessions with no
        routable survivor stay and finish on the draining replica."""
        rep = self.replicas[rid]
        with self._lock:
            pending = list(self._inflight.values())
        moved = 0
        for freq in pending:
            with freq._lock:
                if freq._settled:
                    continue
                atts = [a for a in freq.attempts
                        if not a.failed and a.replica is rep]
            for att in atts:
                target = self._pick_decode_target(freq, exclude={rid})
                if target is None:
                    break
                stats = self._stream_kv(freq, rep, target, "migrate")
                with freq._lock:
                    if att.failed or freq._settled:
                        continue
                # place the survivor attempt BEFORE failing the old one:
                # the poll thread re-dispatches any request whose attempts
                # are all failed, and would race in a duplicate decode
                new_att, _qf = self._place(freq, "migrate",
                                           prefer=target.rid)
                if new_att is None:
                    continue    # no capacity — finish on the drainer
                with freq._lock:
                    if freq._settled:
                        continue
                    att.failed = True
                    freq.migrations += 1
                toks_lost = 0
                try:
                    toks, _s, _r = rep.engine.snapshot_output(att.req)
                    toks_lost = len(toks)
                    rep.engine.cancel(att.req, "migrated")
                except Exception:  # noqa: BLE001 — dying replica
                    pass
                self.obs.on_cancelled(freq, att, toks_lost, "migrated")
                self.obs.on_migrate(freq, rid, target.rid, stats)
                moved += 1
        return moved

    def resume(self, rid: str):
        with self._lock:
            rep = self.replicas[rid]
            rep.engine.resume()
            rep.draining = False

    def drained(self, rid: str) -> bool:
        return self.replicas[rid].engine.drained()

    def kill_replica(self, rid: str):
        """Chaos hook (tests / servebench): crash one replica."""
        self.replicas[rid].kill()

    # -- elastic fleet membership ------------------------------------------
    def add_replica(self, engine=None, *, spec=None,
                    role: str = "any") -> str:
        """Scale-up: join a new replica — either a ServingEngine (thread
        replica) or a ProcessReplicaSpec (supervised OS process; its r20
        warm-up gate keeps it unroutable until /healthz passes). Started
        immediately when the router is running."""
        if (engine is None) == (spec is None):
            raise ValueError("add_replica wants exactly one of engine= "
                             "or spec=")
        if role not in _ROLES:
            raise ValueError(f"unknown fleet role {role!r}")
        max_errors, cooldown = self._breaker_cfg
        with self._lock:
            rid = f"replica-{self._next_rid}"
            self._next_rid += 1
            breaker = CircuitBreaker(max_errors, cooldown,
                                     clock=self._clock)
            if engine is not None:
                rep = Replica(rid, engine, registry=self.registry,
                              heartbeat_s=self._heartbeat_s,
                              breaker=breaker, clock=self._clock,
                              idle_sleep_s=self._idle_sleep_s)
                meta = {"slots": getattr(engine, "max_slots", None)}
            else:
                rep = spec.build(rid, registry=self.registry,
                                 heartbeat_s=self._heartbeat_s,
                                 breaker=breaker, clock=self._clock,
                                 idle_sleep_s=self._idle_sleep_s)
                meta = {"kind": "process"}
            rep.role = role
            self.replicas[rid] = rep
            self._breaker_seen[rid] = "closed"
            self.registry.register(rid, meta=meta)
            started = self._started
            n = len(self.replicas)
        if started:
            rep.start()
        self.obs.on_scale("up", rid, role=role, replicas=n)
        return rid

    def remove_replica(self, rid: str) -> bool:
        """Scale-down (after a drain — ideally migration-assisted — ran
        the replica dry): detach and stop it. In-flight attempts still
        referencing it settle normally; its health gauge drops to 0."""
        with self._lock:
            rep = self.replicas.pop(rid, None)
            self._breaker_seen.pop(rid, None)
            n = len(self.replicas)
        if rep is None:
            return False
        _REPLICA_UP.set(0.0, replica=rid)
        try:
            rep.stop()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass
        self.obs.on_scale("down", rid, role=rep.role, replicas=n)
        return True

    def attach_autoscaler(self, scaler) -> None:
        """Tick `scaler` from every poll (FleetAutoscaler or anything
        with .tick())."""
        self.autoscaler = scaler

    # -- introspection -----------------------------------------------------
    def inflight(self) -> int:
        with self._lock:
            return len(self._inflight)

    def health(self) -> dict:
        """Fleet /healthz body: ok while at least one replica can take
        traffic; per-replica engine snapshots say why not. The whole
        body is assembled under the router lock so the router-level
        fields (inflight, draining, breaker) and every replica snapshot
        come from ONE instant — no replica can die or settle between
        rows of the same response."""
        with self._lock:
            out: Dict[str, dict] = {}
            ok_any = False
            for rid, rep in self.replicas.items():
                dead = self.replica_dead(rep)
                snap = rep.engine.obs.health_snapshot(
                    loop_alive=rep.loop_alive() and not dead)
                snap["breaker"] = rep.breaker.state
                snap["dead"] = dead
                snap["draining"] = rep.draining
                snap["warming"] = rep.warming()
                snap["incarnation"] = rep.incarnation
                snap["pid"] = rep.pid
                snap["respawns"] = rep.respawns
                snap["last_exit"] = rep.last_exit
                out[rid] = snap
                if self.routable(rep):
                    ok_any = True
            return {"ok": ok_any, "inflight": len(self._inflight),
                    "replicas": out}

    def stats(self) -> dict:
        """One consistent router + per-replica snapshot (same locking
        contract as health())."""
        with self._lock:
            reps: Dict[str, dict] = {}
            for rid, rep in self.replicas.items():
                s = rep.engine.stats()
                s["breaker"] = rep.breaker.state
                s["draining"] = rep.draining
                s["dead"] = self.replica_dead(rep)
                s["warming"] = rep.warming()
                s["incarnation"] = rep.incarnation
                s["pid"] = rep.pid
                s["respawns"] = rep.respawns
                s["last_exit"] = rep.last_exit
                reps[rid] = s
            return {"inflight": len(self._inflight), "replicas": reps}


class FleetAutoscaler:
    """Elastic replica-count control over one role pool of a FleetRouter.

    Ticked from every router poll (attach_autoscaler). Utilization is
    offered load over slot capacity across the pool's live replicas;
    crossing `hi` spawns one replica (via the `spawn` callback — a
    ServingEngine for thread replicas or a ProcessReplicaSpec for
    supervised processes, whose r20 warm-up gate keeps the newcomer
    unroutable until healthy), crossing `lo` retires the least-loaded
    one through a migration-assisted drain followed by remove_replica
    once it runs dry. One action per cooldown window; floor/ceiling
    bound the pool. All timing runs on the router's clock, so
    virtual-time benches drive it deterministically."""

    def __init__(self, router: FleetRouter, spawn, *, role: str = "any",
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 hi: Optional[float] = None, lo: Optional[float] = None,
                 cooldown_s: Optional[float] = None,
                 slots_per_replica: int = 8):
        self.router = router
        self.spawn = spawn
        self.role = str(role)
        self.min_replicas = int(_flags.get_flag("fleet_scale_min")
                                if min_replicas is None else min_replicas)
        self.max_replicas = int(_flags.get_flag("fleet_scale_max")
                                if max_replicas is None else max_replicas)
        self.hi = float(_flags.get_flag("fleet_scale_hi")
                        if hi is None else hi)
        self.lo = float(_flags.get_flag("fleet_scale_lo")
                        if lo is None else lo)
        self.cooldown_s = float(_flags.get_flag("fleet_scale_cooldown_s")
                                if cooldown_s is None else cooldown_s)
        if not (0.0 <= self.lo < self.hi):
            raise ValueError(f"need 0 <= lo < hi, got lo={self.lo} "
                             f"hi={self.hi}")
        self.slots_per_replica = int(slots_per_replica)
        self.last_utilization: Optional[float] = None
        self.events: List[dict] = []    # {ts, dir, replica, utilization}
        self._retiring: Optional[str] = None
        self._last_action = -float("inf")

    def _slots(self, rep: Replica) -> int:
        return int(getattr(rep.engine, "max_slots", 0)
                   or self.slots_per_replica)

    def _pool(self) -> List[Replica]:
        return [rep for rep in self.router.replicas.values()
                if rep.role == self.role
                and not rep.draining
                and not self.router.replica_dead(rep)]

    def utilization(self) -> float:
        pool = self._pool()
        cap = sum(self._slots(r) for r in pool)
        if cap <= 0:
            return float("inf")
        return sum(r.load() for r in pool) / cap

    def tick(self) -> Optional[str]:
        """One control turn; returns "up"/"down" when an action fired.
        A pending retirement completes (drained -> removed) before any
        new decision — at most one membership change is ever in flight."""
        now = self.router._clock()
        if self._retiring is not None:
            rid = self._retiring
            if rid not in self.router.replicas:
                self._retiring = None
            else:
                try:
                    dry = self.router.drained(rid)
                except Exception:  # noqa: BLE001 — replica died draining
                    dry = True
                if dry:
                    self.router.remove_replica(rid)
                    self._retiring = None
            return None
        u = self.utilization()
        self.last_utilization = u
        if now - self._last_action < self.cooldown_s:
            return None
        pool = self._pool()
        if u >= self.hi and len(pool) < self.max_replicas:
            new = self.spawn()
            kw = ({"spec": new} if hasattr(new, "build") else
                  {"engine": new})
            rid = self.router.add_replica(role=self.role, **kw)
            self._last_action = now
            self.events.append({"ts": now, "dir": "up", "replica": rid,
                                "utilization": round(u, 4),
                                "replicas": len(pool) + 1})
            return "up"
        if u <= self.lo and len(pool) > self.min_replicas:
            victim = min(pool, key=lambda r: (r.load(), r.rid))
            self.router.drain(victim.rid, migrate=True)
            self._retiring = victim.rid
            self._last_action = now
            self.events.append({"ts": now, "dir": "down",
                                "replica": victim.rid,
                                "utilization": round(u, 4),
                                "replicas": len(pool) - 1})
            return "down"
        return None


def build_fleet(model_factory, n_replicas: Optional[int] = None, *,
                router_kwargs: Optional[dict] = None,
                **engine_kwargs) -> FleetRouter:
    """Build N independent replicas (each its OWN model instance from
    `model_factory` — no shared mutable state between replica threads;
    seed the factory identically for bitwise-interchangeable replicas)
    and a router over them."""
    n = int(_flags.get_flag("fleet_replicas")
            if n_replicas is None else n_replicas)
    engines = [ServingEngine(model_factory(), **engine_kwargs)
               for _ in range(n)]
    return FleetRouter(engines, **(router_kwargs or {}))
