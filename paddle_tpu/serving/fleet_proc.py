"""Process-granularity fleet replicas: real OS processes under a supervisor.

The thread-backed fleet (serving/fleet.py) proves the routing/failover
logic but simulates every fault — kill() is a flag, a "dead" replica's
Python objects are still reachable. This module promotes one replica to a
real subprocess and supervises it the way an agent supervises a pod:

  * ProcessReplica launches ``python -m paddle_tpu.serving.fleet_proc``
    as a child: the child builds its own model + ServingEngine, binds a
    ServingServer on an ephemeral port, prints ONE ready line
    ``{"ready": true, "port": P, "pid": Q}`` and then heartbeats a
    per-incarnation lease into the shared TCPStore.
  * The router speaks to the child over its existing HTTP surface —
    _RemoteEngine/_RemoteRequest duck-type the ServingEngine/Request
    attributes FleetRouter and fleet_observability actually touch, so
    process replicas ride the exact same ``_place()`` path as threads
    (re-dispatch stays bitwise for greedy: the survivor replays the full
    prompt).
  * Death is detected two ways, matching two distinct fault classes:
    waitpid/exit-code for crashes (SIGKILL, OOM, bugs) and heartbeat-
    lease expiry for silent processes (SIGSTOP, network partition). A
    silent-but-alive child gets a heal grace window — a partition that
    heals before the respawn deadline revives the incarnation with NO
    respawn and NO fence bump.
  * Respawn uses resilience/retry.RetryPolicy pacing (capped exponential
    backoff + deterministic jitter, FLAGS_fleet_respawn_max attempts)
    and gates routing on a warm-up probe: the new incarnation is
    ``warming`` (unroutable, not dead) until /healthz says ok.
  * Every incarnation is stamped with a monotonically increasing fence
    token (a store counter bumped before each spawn). The child re-reads
    the counter on every heartbeat and ``os._exit(FENCED_EXIT)``s the
    moment it is superseded — a SIGSTOP'd zombie that wakes after its
    replacement spawned can never serve stale state (satellite: the
    zombie-fencing test drives exactly this SIGSTOP -> lease death ->
    respawn -> SIGCONT -> fence-exit sequence).

Supervisor-side state lives in ProcessReplica.supervise(), called from
every FleetRouter.poll() — the router stays the single supervision loop
for threads and processes alike.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import List, Optional, Tuple

from ..core import flags as _flags
from ..observability.registry import counter as _counter
from ..resilience.retry import RetryPolicy
from .engine import EngineDrainingError, QueueFullError
from .fleet import Replica

_flags.define_flag("fleet_respawn_max", 3,
                   "Respawn attempts per process replica before the "
                   "supervisor gives up and leaves it dead (the initial "
                   "spawn is not counted).")
_flags.define_flag("fleet_respawn_backoff_s", 0.5,
                   "Base respawn backoff in seconds; actual delays follow "
                   "the shared RetryPolicy schedule (exponential, capped "
                   "at 8x base, jittered). Doubles as the heal-grace "
                   "window for a silent-but-alive child.")
_flags.define_flag("fleet_warmup_timeout_s", 60.0,
                   "Seconds a spawned replica incarnation gets to print "
                   "its ready line AND pass the /healthz warm-up probe "
                   "before the supervisor kills it and tries again.")

_RESPAWNS = _counter("fleet_replica_respawns_total",
                     "Process-replica incarnations respawned by the "
                     "supervisor, per replica id.",
                     labelnames=("replica",), always=True)
_FENCED = _counter("fleet_replica_fenced_total",
                   "Zombie incarnations that self-fenced (woke up already "
                   "superseded and exited rather than serve stale state).",
                   always=True)

# the child's self-fence exit code: distinguishable from crashes in
# last_exit and asserted by the zombie-fencing test
FENCED_EXIT = 43

_remote_lock = threading.Lock()
_remote_counter = 0


def _next_remote_id() -> str:
    global _remote_counter
    with _remote_lock:
        _remote_counter += 1
        return f"proc-{_remote_counter}"


def demo_model():
    """Seeded tiny-GPT factory for process replicas (importable by the
    child as ``paddle_tpu.serving.fleet_proc:demo_model``). Seeded like
    tests/test_fleet.py's _model(): every incarnation and every replica
    is bitwise-interchangeable, the property re-dispatch parity rests on."""
    import paddle_tpu as paddle
    from ..models import GPTConfig, GPTForCausalLM

    paddle.seed(11)
    cfg = GPTConfig.tiny()
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


# ---------------------------------------------------------------------------
# remote engine: the router-facing duck type over the child's HTTP surface
# ---------------------------------------------------------------------------

class _RemoteRequest:
    """Client-side mirror of one generation request running in the child.
    Duck-types the serving.scheduler.Request attributes the router and
    fleet_observability touch: identity, token/state snapshots, lifecycle
    timestamps (this process's monotonic clock) and telemetry (the
    child's own telemetry block rides back on the final stream line)."""

    def __init__(self, prompt: List[int], max_new_tokens: int,
                 temperature: float, eos_token_id, request_id: Optional[str],
                 tier: str, trace_ctx: Optional[dict]):
        self.request_id = request_id or _next_remote_id()
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.eos_token_id = eos_token_id
        self.tier = str(tier) if tier else "default"
        self.trace = None               # engine-side spans stay in the child
        self.trace_ctx = dict(trace_ctx) if trace_ctx else None
        self.state = "queued"
        self.finish_reason: Optional[str] = None
        self.output_tokens: List[int] = []
        self.prefix_matched = 0
        self.arrival_time = time.monotonic()
        self.prefill_start: Optional[float] = None
        self.first_token_time: Optional[float] = None
        self.finish_time: Optional[float] = None
        self._remote_telemetry: Optional[dict] = None
        self._cancelled = False
        self._resp = None               # live HTTP response (stream)
        self._lock = threading.Lock()
        self._done = threading.Event()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def queue_seconds(self) -> Optional[float]:
        if self.prefill_start is None:
            return None
        return self.prefill_start - self.arrival_time

    def ttft_seconds(self) -> Optional[float]:
        if self.first_token_time is None:
            return None
        return self.first_token_time - self.arrival_time

    def decode_tokens_per_s(self) -> Optional[float]:
        if self.finish_time is None or self.first_token_time is None:
            return None
        n = len(self.output_tokens)
        dt = self.finish_time - self.first_token_time
        return (n - 1) / dt if n > 1 and dt > 0 else None

    def telemetry(self) -> dict:
        with self._lock:
            remote = dict(self._remote_telemetry or {})
        t = {
            "request_id": self.request_id,
            "tier": self.tier,
            "state": self.state,
            "finish_reason": self.finish_reason,
            "prompt_tokens": len(self.prompt),
            "prefix_matched_tokens": self.prefix_matched,
            "output_tokens": len(self.output_tokens),
            "queue_s": self.queue_seconds(),
            "ttft_s": self.ttft_seconds(),
            "decode_tok_s": self.decode_tokens_per_s(),
        }
        # the child's telemetry is the authoritative engine view (its
        # queue/prefix numbers); keep the router-side identity fields
        for k, v in remote.items():
            if k not in ("request_id", "tier", "state", "finish_reason"):
                t[k] = v
        return t


class _RemoteObs:
    """`engine.obs` facade: health_snapshot proxies the child /healthz."""

    def __init__(self, engine: "_RemoteEngine"):
        self._engine = engine

    def health_snapshot(self, loop_alive: bool = True) -> dict:
        snap = self._engine._get_json("/healthz", ok_codes=(200, 503))
        if snap is None:
            snap = {"ok": False, "status": "unreachable"}
        snap["loop_alive"] = bool(loop_alive) and bool(snap.get("ok"))
        snap["remote"] = True
        return snap


class _RemoteEngine:
    """ServingEngine duck type over one child incarnation's HTTP surface.
    submit() opens a streaming POST /generate and a daemon reader thread
    feeds the _RemoteRequest; cancel() severs the stream socket, which
    the child's server turns into an engine-side disconnect-cancel (slot
    and KV reservation freed). One _RemoteEngine per incarnation — after
    a respawn the replica swaps in a fresh one and requests still bound
    to the dead incarnation fail out and re-dispatch."""

    _HTTP_TIMEOUT_S = 5.0

    def __init__(self, base_url: Optional[str]):
        self.base_url = base_url        # None: incarnation not up yet
        self.obs = _RemoteObs(self)
        self._draining = False
        self._inflight = 0
        self._lock = threading.Lock()

    # -- shared HTTP helpers ------------------------------------------------
    def _get_json(self, path: str, ok_codes=(200,),
                  timeout: Optional[float] = None) -> Optional[dict]:
        if self.base_url is None:
            return None
        try:
            with urllib.request.urlopen(
                    self.base_url + path,
                    timeout=timeout or self._HTTP_TIMEOUT_S) as resp:
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            if e.code in ok_codes:
                try:
                    return json.loads(e.read().decode())
                except Exception:  # noqa: BLE001 — torn body
                    return None
            return None
        except Exception:  # noqa: BLE001 — dead/frozen child
            return None

    # -- engine surface used by the router ----------------------------------
    def submit(self, prompt: List[int], max_new_tokens: int = 16,
               temperature: float = 0.0, eos_token_id=None,
               request_id: Optional[str] = None, tier: str = "default",
               trace_ctx: Optional[dict] = None,
               prefill_only: bool = False) -> _RemoteRequest:
        if self.base_url is None:
            raise RuntimeError("replica incarnation not ready")
        if self._draining:
            raise EngineDrainingError()
        req = _RemoteRequest(prompt, max_new_tokens, temperature,
                             eos_token_id, request_id, tier, trace_ctx)
        body = json.dumps({
            "prompt": req.prompt, "max_new_tokens": req.max_new_tokens,
            "temperature": req.temperature, "eos_token_id": req.eos_token_id,
            "tier": req.tier, "stream": True,
            "prefill_only": bool(prefill_only),
        }).encode()
        http_req = urllib.request.Request(
            self.base_url + "/generate", data=body,
            headers={"Content-Type": "application/json"})
        try:
            resp = urllib.request.urlopen(http_req,
                                          timeout=self._HTTP_TIMEOUT_S)
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read().decode())
            except Exception:  # noqa: BLE001
                detail = {}
            if e.code == 503:
                raise QueueFullError(int(detail.get("queue_depth", 0)),
                                     int(detail.get("queue_limit", 0)))
            if e.code == 400:
                raise ValueError(detail.get("error", "bad request"))
            raise RuntimeError(f"remote submit: HTTP {e.code}")
        except OSError as e:
            # dead/unreachable child between placement and submit: a
            # replica fault the router's _place turns into the next
            # candidate (or a re-dispatch), never a caller-visible
            # transport exception
            raise RuntimeError(f"remote submit failed: {e}")
        req._resp = resp
        req.state = "running"
        req.prefill_start = time.monotonic()
        with self._lock:
            self._inflight += 1
        threading.Thread(target=self._consume, args=(req, resp),
                         name="fleet-proc-stream", daemon=True).start()
        return req

    def _consume(self, req: _RemoteRequest, resp) -> None:
        """Reader thread: one NDJSON line per child flush. Any transport
        fault marks the request finished with a non-good reason, which
        the router's settle pass turns into a failed attempt -> the
        request re-dispatches even when the replica itself is judged
        alive (e.g. the child restarted between placement and finish)."""
        reason = "error"
        telemetry = None
        try:
            while True:
                line = resp.readline()
                if not line:
                    break
                line = line.strip()
                if not line:
                    continue
                msg = json.loads(line.decode())
                with req._lock:
                    toks = msg.get("tokens")
                    if toks:
                        if req.first_token_time is None:
                            req.first_token_time = time.monotonic()
                        req.output_tokens.extend(int(t) for t in toks)
                    if msg.get("done"):
                        reason = msg.get("finish_reason") or "stop"
                        telemetry = msg.get("telemetry")
                        break
        except Exception:  # noqa: BLE001 — severed stream
            pass
        finally:
            try:
                resp.close()
            except Exception:  # noqa: BLE001
                pass
            with self._lock:
                self._inflight = max(0, self._inflight - 1)
            with req._lock:
                if req._cancelled and reason == "error":
                    reason = "cancelled"
                if req.finish_reason is None:
                    req.finish_reason = reason
                if telemetry:
                    req._remote_telemetry = telemetry
                req.state = "finished"
                req.finish_time = time.monotonic()
            req._done.set()

    def snapshot_output(self, req: _RemoteRequest
                        ) -> Tuple[List[int], str, Optional[str]]:
        with req._lock:
            return list(req.output_tokens), req.state, req.finish_reason

    def cancel(self, req: _RemoteRequest, reason: str = "cancelled") -> bool:
        with req._lock:
            if req.state == "finished":
                return False
            req._cancelled = True
            req.finish_reason = reason
            resp = req._resp
        # severing the stream socket is the cancel signal: the child's
        # handler sees the broken pipe and engine-cancels the request
        if resp is not None:
            try:
                resp.close()
            except Exception:  # noqa: BLE001
                pass
        return True

    def drain(self) -> None:
        self._draining = True

    def resume(self) -> None:
        self._draining = False

    def drained(self) -> bool:
        with self._lock:
            return self._draining and self._inflight == 0

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def stats(self) -> dict:
        s = self._get_json("/stats", timeout=2.0)
        if s is None:
            return {"remote": True, "unreachable": True}
        s["remote"] = True
        return s

    # -- KV-block transfer wire (disagg streaming / live migration) ---------
    def export_kv_blocks(self, tokens: List[int]) -> List[dict]:
        """POST /kv/export on the child; decoded to the same record list
        ServingEngine.export_kv_blocks returns. Best-effort: a dead or
        frozen child exports nothing (the receiver just re-prefils)."""
        if self.base_url is None:
            return []
        from .server import kv_wire_decode

        body = json.dumps({"tokens": [int(t) for t in tokens]}).encode()
        http_req = urllib.request.Request(
            self.base_url + "/kv/export", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(
                    http_req, timeout=self._HTTP_TIMEOUT_S) as resp:
                return kv_wire_decode(resp.read())
        except Exception:  # noqa: BLE001 — unreachable child
            return []

    def ingest_kv_blocks(self, records: List[dict]) -> dict:
        """POST /kv/ingest on the child; raises on an unreachable child
        so the router's transfer path falls back to plain re-prefill."""
        if self.base_url is None:
            raise RuntimeError("replica incarnation not ready")
        from .server import kv_wire_encode

        http_req = urllib.request.Request(
            self.base_url + "/kv/ingest", data=kv_wire_encode(records),
            headers={"Content-Type": "application/x-ndjson"})
        with urllib.request.urlopen(
                http_req, timeout=self._HTTP_TIMEOUT_S) as resp:
            return json.loads(resp.read().decode())


# ---------------------------------------------------------------------------
# supervisor side
# ---------------------------------------------------------------------------

class ProcessReplicaSpec:
    """Recipe FleetRouter.__init__ turns into a ProcessReplica (the
    router passes registry/breaker/clock; the spec carries everything
    process-specific). ``child_store_addr`` lets chaos tests route the
    CHILD's store client through a StorePartitionProxy while the
    supervisor keeps its direct connection."""

    def __init__(self, store_addr: Tuple[str, int], *,
                 factory: str = "paddle_tpu.serving.fleet_proc:demo_model",
                 engine_kwargs: Optional[dict] = None,
                 child_store_addr: Optional[Tuple[str, int]] = None,
                 child_heartbeat_s: float = 0.2,
                 warmup_timeout_s: Optional[float] = None,
                 respawn_max: Optional[int] = None,
                 respawn_backoff_s: Optional[float] = None,
                 python: str = sys.executable,
                 extra_env: Optional[dict] = None):
        self.store_addr = (str(store_addr[0]), int(store_addr[1]))
        self.child_store_addr = (tuple(child_store_addr)
                                 if child_store_addr else self.store_addr)
        self.factory = str(factory)
        self.engine_kwargs = dict(engine_kwargs or {})
        self.child_heartbeat_s = float(child_heartbeat_s)
        self.warmup_timeout_s = float(
            _flags.get_flag("fleet_warmup_timeout_s")
            if warmup_timeout_s is None else warmup_timeout_s)
        self.respawn_max = int(_flags.get_flag("fleet_respawn_max")
                               if respawn_max is None else respawn_max)
        self.respawn_backoff_s = float(
            _flags.get_flag("fleet_respawn_backoff_s")
            if respawn_backoff_s is None else respawn_backoff_s)
        self.python = str(python)
        self.extra_env = dict(extra_env or {})

    def build(self, rid: str, *, registry, heartbeat_s: float, breaker,
              clock=time.monotonic, idle_sleep_s: float = 0.002
              ) -> "ProcessReplica":
        return ProcessReplica(rid, self, registry=registry,
                              heartbeat_s=heartbeat_s, breaker=breaker,
                              clock=clock, idle_sleep_s=idle_sleep_s)


class ProcessReplica(Replica):
    """A fleet replica whose engine lives in a supervised subprocess.

    Lifecycle (all transitions happen in supervise(), which the router
    calls every poll; spawns run in a daemon thread because the child's
    jax import takes seconds and must not stall the monitor):

        spawning -> warming -> ready --(exit / lease death)--> suspect
          ^                                   |                   |
          |                          heal grace (alive +          |
          |                          lease revived: ready,        |
          |                          NO respawn/fence bump)       |
          +--- backoff deadline, fence bump, respawn <------------+

    ``kill()`` SIGKILLs the child (real chaos, supervisor respawns it);
    use ``retire()`` for the thread-replica "dead forever" semantics.
    """

    def __init__(self, rid: str, spec: ProcessReplicaSpec, *, registry,
                 heartbeat_s: float, breaker, clock=time.monotonic,
                 idle_sleep_s: float = 0.002):
        super().__init__(rid, _RemoteEngine(None), registry=registry,
                         heartbeat_s=heartbeat_s, breaker=breaker,
                         clock=clock, idle_sleep_s=idle_sleep_s)
        self.spec = spec
        self.pid = None                 # child pid once ready
        self._proc: Optional[subprocess.Popen] = None
        self._ready = False
        self._stopped = False
        self._exhausted = False
        self._spawning = False
        self._spawn_thread: Optional[threading.Thread] = None
        self._next_spawn_at: Optional[float] = 0.0   # spawn ASAP on start
        self._suspect_deadline: Optional[float] = None
        self._zombies: List[subprocess.Popen] = []   # orphaned incarnations
        self._sup_lock = threading.RLock()
        self._backoff = RetryPolicy(
            base_delay=spec.respawn_backoff_s,
            max_delay=spec.respawn_backoff_s * 8.0,
            multiplier=2.0, jitter=0.5, name=f"respawn-{rid}")

    # -- identity -----------------------------------------------------------
    def _lease_id(self) -> str:
        """Per-incarnation lease id: a zombie beating its OLD lease can
        never refresh the CURRENT incarnation's liveness."""
        return f"{self.rid}@{self.incarnation}"

    def _fence_key(self) -> str:
        return f"{self.registry.prefix}/fence/{self.rid}"

    # -- Replica surface overrides -------------------------------------------
    def start(self):
        # the spawn is asynchronous (child jax import takes seconds);
        # the replica stays `warming` until the warm-up probe passes
        with self._sup_lock:
            if self._stopped or self._proc is not None or self._spawning:
                return
            self._begin_spawn()

    def stop(self):
        with self._sup_lock:
            self._stopped = True
            procs = [p for p in [self._proc] + self._zombies if p is not None]
            self._proc = None
            self._zombies = []
        for p in procs:
            try:
                p.terminate()
            except OSError:
                pass
        deadline = time.monotonic() + 5.0
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except (subprocess.TimeoutExpired, OSError):
                try:
                    p.kill()
                    p.wait(timeout=5.0)
                except OSError:
                    pass

    def kill(self):
        """Chaos hook: SIGKILL the live incarnation. Unlike the thread
        replica this is not terminal — the supervisor detects the exit
        and respawns under backoff."""
        with self._sup_lock:
            proc = self._proc
        if proc is not None:
            try:
                os.kill(proc.pid, signal.SIGKILL)
            except OSError:
                pass

    def retire(self):
        """Terminal kill: thread-replica kill() semantics (dead forever,
        no respawn)."""
        self._killed = True
        self.stop()

    def loop_alive(self) -> bool:
        with self._sup_lock:
            return (self._proc is not None and self._proc.poll() is None
                    and self._ready)

    def pause(self):  # pragma: no cover — chaos uses SIGSTOP directly
        raise NotImplementedError(
            "use resilience.chaos.hang_process(replica.pid) for process "
            "replicas")

    def dead(self, lease_ttl_s: float) -> bool:
        if self._killed or self._stopped or self._exhausted:
            return True
        with self._sup_lock:
            proc, ready = self._proc, self._ready
        if proc is None:
            # between incarnations (awaiting backoff), mid-spawn, or
            # never started: dead for routing/redispatch purposes
            return True
        if proc.poll() is not None:
            return True
        if not ready:
            return False                # warming: alive, just not routable
        return not self.registry.alive(self._lease_id(), float(lease_ttl_s))

    def warming(self) -> bool:
        return not self._ready and not self._stopped and not self._killed

    # -- routing probes (no remote round trip on the hot path) ---------------
    def load(self) -> int:
        return self.engine.inflight()

    def affinity(self, prompt: List[int]) -> int:
        # probing the child's prefix cache would cost an HTTP round trip
        # per candidate per placement; process replicas bid 0 and win on
        # least-load / id order instead
        return 0

    def queue_depth(self) -> int:
        return self.engine.inflight()

    # -- supervision state machine -------------------------------------------
    def supervise(self, router) -> None:
        now = self._clock()
        with self._sup_lock:
            self._reap_zombies()
            if self._stopped or self._killed or self._exhausted \
                    or self._spawning:
                return
            proc = self._proc
            if proc is None:
                if self._next_spawn_at is not None \
                        and now >= self._next_spawn_at:
                    self._begin_spawn()
                return
            code = proc.poll()
            if code is not None:
                self._on_exit(code, now)
                return
            if not self._ready:
                return
            if self.registry.alive(self._lease_id(), router.lease_ttl_s):
                if self._suspect_deadline is not None:
                    # silent spell healed before the respawn deadline
                    # (partition heal): revive with NO respawn, NO fence
                    self._suspect_deadline = None
                    self._note("fleet_replica_lease_revived",
                               replica=self.rid,
                               incarnation=self.incarnation)
                return
            # alive by waitpid, dead by lease: silent process
            if self._suspect_deadline is None:
                grace = self._backoff.jittered_delay(self.respawns + 1)
                self._suspect_deadline = now + grace
                self._note("fleet_replica_lease_expired", replica=self.rid,
                           incarnation=self.incarnation, pid=proc.pid,
                           heal_grace_s=round(grace, 3))
                return
            if now < self._suspect_deadline:
                return
            # grace over and still silent: orphan the incarnation (do NOT
            # kill it — if it ever wakes it must fence itself out) and
            # respawn under a fresh fence token
            self._suspect_deadline = None
            self._zombies.append(proc)
            self._proc = None
            self._ready = False
            self._record_exit(exit_code=None, reason="lease_expired",
                              pid=proc.pid)
            self._schedule_respawn(now, immediate=True)

    def _reap_zombies(self) -> None:
        """Poll orphaned incarnations (supervision lock held). A zombie
        that woke from SIGSTOP and found itself superseded exits with
        FENCED_EXIT — the proof it never served stale state."""
        for z in list(self._zombies):
            zc = z.poll()
            if zc is None:
                continue
            self._zombies.remove(z)
            if zc == FENCED_EXIT:
                _FENCED.inc()
                self._note("fleet_replica_fenced", replica=self.rid,
                           pid=z.pid, exit_code=zc)
                self.last_exit = dict(self.last_exit or {},
                                      fenced_pid=z.pid)
            else:
                self._note("fleet_replica_zombie_reaped", replica=self.rid,
                           pid=z.pid, exit_code=zc)

    def _on_exit(self, code: int, now: float) -> None:
        """Child exited (waitpid path). Classify, record, schedule."""
        proc, self._proc = self._proc, None
        self._ready = False
        self._suspect_deadline = None
        if code == FENCED_EXIT:
            # a superseded zombie draining out is bookkeeping, not a
            # fault: no respawn churn for it
            _FENCED.inc()
            self._note("fleet_replica_fenced", replica=self.rid,
                       pid=proc.pid if proc else None)
            self.last_exit = dict(self.last_exit or {},
                                  fenced_pid=proc.pid if proc else None)
            return
        self._record_exit(exit_code=code, reason="exit",
                          pid=proc.pid if proc else None)
        self._schedule_respawn(now)

    def _record_exit(self, *, exit_code, reason: str, pid) -> None:
        self.last_exit = {
            "incarnation": self.incarnation,
            "pid": pid,
            "exit_code": exit_code,
            "reason": reason,
        }
        self._note("fleet_replica_dead", replica=self.rid, **self.last_exit)

    def _schedule_respawn(self, now: float, immediate: bool = False) -> None:
        if self.respawns >= self.spec.respawn_max:
            self._exhausted = True
            if self.last_exit is not None:
                self.last_exit["respawn_budget_exhausted"] = True
            self._note("fleet_replica_respawn_exhausted", replica=self.rid,
                       respawns=self.respawns)
            return
        # the heal-grace window already consumed the backoff for the
        # silent-death path; crashes wait it out before respawning
        delay = (0.0 if immediate
                 else self._backoff.jittered_delay(self.respawns + 1))
        self._next_spawn_at = now + delay

    # -- spawn ----------------------------------------------------------------
    def _begin_spawn(self) -> None:
        """Arm a spawn (supervision lock held). The heavy lifting —
        fence bump, fork/exec, ready line, warm-up probe — runs in a
        daemon thread so a multi-second child cold start never stalls
        the router's poll loop."""
        self._spawning = True
        self._next_spawn_at = None
        respawn = self._proc is not None or self.incarnation > 0
        self._spawn_thread = threading.Thread(
            target=self._spawn, args=(respawn,),
            name=f"fleet-spawn-{self.rid}", daemon=True)
        self._spawn_thread.start()

    def _spawn(self, respawn: bool) -> None:
        try:
            # the fence bump is the point of no return for the previous
            # incarnation: from here any survivor of it must self-fence
            fence = int(self.registry.store.add(self._fence_key(), 1))
            if respawn:
                with self._sup_lock:
                    self.respawns += 1
                _RESPAWNS.inc(replica=self.rid)
                self._dump_respawn(fence)
            host, port = self.spec.child_store_addr
            cmd = [
                self.spec.python, "-m", "paddle_tpu.serving.fleet_proc",
                "--replica-id", self.rid,
                "--incarnation", str(fence),
                "--fence", str(fence),
                "--store", f"{host}:{port}",
                "--prefix", self.registry.prefix,
                "--factory", self.spec.factory,
                "--engine-kwargs", json.dumps(self.spec.engine_kwargs),
                "--heartbeat-s", str(self.spec.child_heartbeat_s),
                "--parent-pid", str(os.getpid()),
            ]
            env = dict(os.environ)
            env.setdefault("JAX_PLATFORMS", "cpu")
            env.update(self.spec.extra_env)
            proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                    stderr=subprocess.DEVNULL, env=env)
            deadline = time.monotonic() + self.spec.warmup_timeout_s
            # a child that hangs before its ready line would park
            # readline forever; the watchdog kills it at the deadline so
            # the pipe EOFs and the spawn fails over to the next attempt
            watchdog = threading.Timer(
                max(0.1, deadline - time.monotonic()), self._reap,
                args=(proc,))
            watchdog.daemon = True
            watchdog.start()
            try:
                ready = self._await_ready(proc, deadline)
            finally:
                watchdog.cancel()
            if ready is None:
                self._spawn_failed(proc, "warmup_timeout", fence)
                return
            engine = _RemoteEngine(f"http://127.0.0.1:{ready['port']}")
            if not self._probe(engine, deadline):
                self._spawn_failed(proc, "warmup_probe_failed", fence)
                return
            with self._sup_lock:
                if self._stopped or self._killed:
                    self._reap(proc)
                    return
                self.incarnation = fence
                self.pid = proc.pid
                self.engine = engine
                self._proc = proc
                self._ready = True
                self._suspect_deadline = None
                self._note("fleet_replica_ready", replica=self.rid,
                           incarnation=fence, pid=proc.pid,
                           port=ready["port"])
        except Exception as e:  # noqa: BLE001 — spawn machinery fault
            with self._sup_lock:
                self._record_exit(exit_code=None,
                                  reason=f"spawn_error: {e}", pid=None)
                self._schedule_respawn(self._clock())
        finally:
            with self._sup_lock:
                self._spawning = False

    def _await_ready(self, proc: subprocess.Popen,
                     deadline: float) -> Optional[dict]:
        """Block (spawn thread only) for the child's single ready line;
        afterwards a drain thread keeps the pipe from filling."""
        line = proc.stdout.readline() if proc.stdout else b""
        while line and time.monotonic() < deadline:
            line = line.strip()
            if line.startswith(b"{"):
                try:
                    msg = json.loads(line.decode())
                except ValueError:
                    msg = {}
                if msg.get("ready"):
                    threading.Thread(target=self._drain_stdout, args=(proc,),
                                     name=f"fleet-drain-{self.rid}",
                                     daemon=True).start()
                    return msg
            line = proc.stdout.readline()
        return None

    @staticmethod
    def _drain_stdout(proc: subprocess.Popen) -> None:
        try:
            while proc.stdout and proc.stdout.read(65536):
                pass
        except Exception:  # noqa: BLE001
            pass

    def _probe(self, engine: _RemoteEngine, deadline: float) -> bool:
        """Warm-up gate: the incarnation takes traffic only once its own
        /healthz agrees it is healthy."""
        while time.monotonic() < deadline:
            if self._stopped:
                return False
            snap = engine._get_json("/healthz", ok_codes=(200, 503),
                                    timeout=2.0)
            if snap is not None and snap.get("ok"):
                return True
            time.sleep(0.1)
        return False

    def _spawn_failed(self, proc: subprocess.Popen, reason: str,
                      fence: int) -> None:
        self._reap(proc)
        with self._sup_lock:
            self._record_exit(exit_code=proc.poll(), reason=reason,
                              pid=proc.pid)
            self._schedule_respawn(self._clock())

    @staticmethod
    def _reap(proc: subprocess.Popen) -> None:
        try:
            proc.kill()
            proc.wait(timeout=5.0)
        except OSError:
            pass

    # -- observability --------------------------------------------------------
    @staticmethod
    def _note(kind: str, **data) -> None:
        try:
            from ..observability.flight_recorder import get_flight_recorder
            get_flight_recorder().note(kind, **data)
        except Exception:  # noqa: BLE001 — observability must not wound
            pass

    def _dump_respawn(self, new_fence: int) -> None:
        """Flight-recorder dump on every respawn, embedding the dead
        incarnation's last recorded state (satellite 3)."""
        try:
            from ..observability.flight_recorder import get_flight_recorder
            get_flight_recorder().dump(
                "fleet_respawn",
                extra={"replica": self.rid,
                       "dead_incarnation": dict(self.last_exit or {}),
                       "new_incarnation": int(new_fence),
                       "respawns_so_far": self.respawns})
        except Exception:  # noqa: BLE001
            pass


def build_process_fleet(n_replicas: int = 2, *, store,
                        store_addr: Tuple[str, int],
                        spec_kwargs: Optional[dict] = None,
                        router_kwargs: Optional[dict] = None):
    """N supervised process replicas behind one FleetRouter sharing
    `store` (a native TCPStore master the caller owns; `store_addr` is
    the endpoint the CHILDREN dial — point it at a chaos proxy to
    partition them). Returns the router unstarted."""
    from .fleet import FleetRouter

    specs = [ProcessReplicaSpec(store_addr, **(spec_kwargs or {}))
             for _ in range(int(n_replicas))]
    kw = dict(router_kwargs or {})
    return FleetRouter(replica_specs=specs, store=store, **kw)


def wait_fleet_ready(router, timeout_s: float = 120.0) -> bool:
    """Poll until every process replica passed its warm-up probe (thread
    replicas count as ready immediately). Drives router.poll() itself so
    it also works on an unstarted router."""
    deadline = time.monotonic() + float(timeout_s)
    while time.monotonic() < deadline:
        router.poll()
        if all(not rep.warming() for rep in router.replicas.values()):
            return True
        time.sleep(0.05)
    return False


# ---------------------------------------------------------------------------
# child side (python -m paddle_tpu.serving.fleet_proc)
# ---------------------------------------------------------------------------

def _load_factory(spec: str):
    mod_name, _, fn_name = spec.rpartition(":")
    if not mod_name:
        raise ValueError(f"factory must be 'module:function', got {spec!r}")
    import importlib

    return getattr(importlib.import_module(mod_name), fn_name)


def _child_main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="paddle_tpu.serving.fleet_proc")
    p.add_argument("--replica-id", required=True)
    p.add_argument("--incarnation", type=int, required=True)
    p.add_argument("--fence", type=int, required=True)
    p.add_argument("--store", required=True, help="host:port of the "
                   "fleet TCPStore (possibly via a partition proxy)")
    p.add_argument("--prefix", default="/pt/fleet")
    p.add_argument("--factory",
                   default="paddle_tpu.serving.fleet_proc:demo_model")
    p.add_argument("--engine-kwargs", default="{}")
    p.add_argument("--heartbeat-s", type=float, default=0.2)
    p.add_argument("--parent-pid", type=int, default=0)
    args = p.parse_args(argv)

    from .. import native
    from ..distributed.env import ReplicaRegistry
    from .engine import ServingEngine
    from .server import ServingServer

    host, _, port = args.store.rpartition(":")
    store = native.TCPStore(host, int(port), is_master=False, world_size=1,
                            timeout_s=30.0)
    registry = ReplicaRegistry(store, prefix=args.prefix)
    lease = f"{args.replica_id}@{args.incarnation}"
    fence_key = f"{args.prefix}/fence/{args.replica_id}"

    # refuse to even build the model when already superseded (a spawn
    # that lost a race with a faster supervisor decision)
    if int(store.add(fence_key, 0)) != args.fence:
        return FENCED_EXIT

    model = _load_factory(args.factory)()
    engine = ServingEngine(model, **json.loads(args.engine_kwargs))
    srv = ServingServer(engine, port=0)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())

    print(json.dumps({"ready": True, "port": srv.port, "pid": os.getpid()}),
          flush=True)

    while not stop.is_set():
        # fence check FIRST: a zombie waking from SIGSTOP must exit
        # before it heartbeats or serves anything (os._exit: no atexit,
        # no socket flush — the process is gone like it was never woken)
        if int(store.add(fence_key, 0)) != args.fence:
            os._exit(FENCED_EXIT)
        if args.parent_pid and os.getppid() != args.parent_pid:
            break                        # supervisor died: no orphans
        registry.heartbeat(lease)
        stop.wait(args.heartbeat_s)

    srv.stop()
    try:
        store.close()
    except Exception:  # noqa: BLE001
        pass
    return 0


if __name__ == "__main__":
    sys.exit(_child_main())
