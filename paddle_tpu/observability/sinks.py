"""Metric sinks: append-only JSONL event log + Prometheus textfile exporter.

Two write disciplines, matched to what each consumer needs:

  * JsonlEventLog — one JSON object per line, flushed per write. Append-only
    so a crash can only lose the final partial line (readers skip it); the
    flight recorder and tools/stepbench.py read this file back.
  * Prometheus textfile — the node-exporter "textfile collector" contract:
    the WHOLE exposition is rewritten atomically (tmp + os.replace, the same
    discipline as resilience/checkpoint_manager.py) so a scraper never sees
    a torn file. `parse_prometheus_text` round-trips it for tests.
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Dict, Optional, Tuple

from .registry import Histogram, MetricsRegistry, default_registry

PROM_FILENAME = "paddle_tpu.prom"
EVENTS_FILENAME = "events.jsonl"


class JsonlEventLog:
    """Append-only JSONL writer; thread-safe; flushes every record."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        self._lock = threading.Lock()
        self._f = None

    def emit(self, record: Dict) -> None:
        line = json.dumps(record, default=_json_default)
        with self._lock:
            if self._f is None:
                os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
                self._f = open(self.path, "a", encoding="utf-8")
            self._f.write(line + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _json_default(obj):
    """Telemetry records may carry numpy/jax scalars; never let a dtype kill
    the event log."""
    try:
        return float(obj)
    except (TypeError, ValueError):
        return repr(obj)


def _escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def prometheus_text(registry: Optional[MetricsRegistry] = None) -> str:
    """Render the registry in the Prometheus text exposition format."""
    registry = registry or default_registry()
    lines = []
    for m in sorted(registry.metrics(), key=lambda m: m.name):
        if m.doc:
            lines.append(f"# HELP {m.name} {m.doc}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        if isinstance(m, Histogram):
            for labels, value, series in m.samples():
                lines.append(_sample_line(series, labels, value))
            continue
        samples = m.samples()
        if not samples:  # registered but never recorded: expose the zero
            lines.append(_sample_line(m.name, {}, 0.0))
        for labels, value in samples:
            lines.append(_sample_line(m.name, labels, value))
    return "\n".join(lines) + "\n"


def _sample_line(series: str, labels: Dict[str, str], value: float) -> str:
    if labels:
        lbl = ",".join(f'{k}="{_escape(v)}"' for k, v in sorted(labels.items()))
        return f"{series}{{{lbl}}} {_fmt(value)}"
    return f"{series} {_fmt(value)}"


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def write_prometheus_textfile(path: str,
                              registry: Optional[MetricsRegistry] = None
                              ) -> str:
    """Atomically (re)write the full exposition at `path`."""
    text = prometheus_text(registry)
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".prom.tmp")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(text)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def parse_prometheus_text(text: str) -> Dict[Tuple[str, Tuple[Tuple[str, str],
                                                              ...]], float]:
    """Inverse of prometheus_text for round-trip tests:
    {(series_name, ((label, value), ...sorted)): sample_value}."""
    out: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_part, _, val_part = line.rpartition(" ")
        if "{" in name_part:
            series, _, rest = name_part.partition("{")
            lbls = []
            body = rest.rstrip("}")
            # split on commas outside quotes
            cur, in_q, parts = "", False, []
            for ch in body:
                if ch == '"' and not cur.endswith("\\"):
                    in_q = not in_q
                if ch == "," and not in_q:
                    parts.append(cur)
                    cur = ""
                else:
                    cur += ch
            if cur:
                parts.append(cur)
            for p in parts:
                k, _, v = p.partition("=")
                v = v.strip('"').replace(r"\"", '"').replace(r"\n", "\n") \
                     .replace(r"\\", "\\")
                lbls.append((k, v))
            key = (series, tuple(sorted(lbls)))
        else:
            key = (name_part, ())
        out[key] = float(val_part)
    return out
