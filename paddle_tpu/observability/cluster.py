"""Cross-rank telemetry aggregation + straggler detection.

PR 4 gave every process its own registry/telemetry/flight recorder; nothing
could answer "which rank is slow". Here each rank publishes a slim per-step
record (phase timings, loss, grad-norm, throughput) into the process-group
KV store (native TCPStore on a real multi-host job, distributed/env.py's
InProcStore when threads simulate ranks), and rank 0 aggregates:

  * per-phase min / median / max / p95 across ranks -> `cluster_*` gauges
    and one `cluster_step` JSONL event per step;
  * straggler flagging (the T3 observation, arXiv 2401.16677: overlap decay
    is invisible without per-phase, per-rank tracking): a rank whose
    `compute` or `reduce` phase exceeds FLAGS_straggler_k x the cross-rank
    median for FLAGS_straggler_m CONSECUTIVE steps is flagged — a
    structured `straggler` event goes to the JSONL/Prometheus sinks and the
    flight recorder's cluster snapshot, so a later crash dump says which
    rank was dragging and since when.

The store is the transport on purpose: it already exists (rendezvous), it
is tiny (one small JSON value per rank per in-flight step, deleted after
aggregation), and it needs no collective — a hung rank degrades to a
timeout, not a deadlocked all-gather.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from . import flight_recorder, telemetry
from .registry import counter, gauge
from ..core.flags import define_flag, get_flag

define_flag(
    "straggler_k", 2.0,
    "Cluster straggler threshold: a rank is straggling when its compute or "
    "reduce phase exceeds k x the cross-rank median of that phase.")
define_flag(
    "straggler_m", 3,
    "Cluster straggler persistence: consecutive over-threshold steps before "
    "a rank is flagged (debounces one-off scheduler hiccups).")

# the per-rank fields worth shipping cross-host (keep the value tiny: it
# crosses the store once per rank per step)
_SLIM_FIELDS = ("step", "loss", "grad_norm", "step_wall_s",
                "samples_per_s", "tokens_per_s", "skipped")
_STATS = ("min", "median", "max", "p95")
_STRAGGLER_PHASES = ("compute", "reduce")

_PHASE_G = gauge("cluster_phase_seconds",
                 "Cross-rank per-step phase time distribution.",
                 labelnames=("phase", "stat"))
_LOSS_G = gauge("cluster_loss", "Cross-rank loss distribution of the last "
                "aggregated step.", labelnames=("stat",))
_TPS_G = gauge("cluster_tokens_per_second_total",
               "Summed tokens/s across all ranks (last aggregated step).")
_SPS_G = gauge("cluster_samples_per_second_total",
               "Summed samples/s across all ranks (last aggregated step).")
_WALL_G = gauge("cluster_step_wall_seconds",
                "Cross-rank step wall-time distribution.",
                labelnames=("stat",))
_STRAGGLERS = counter("cluster_straggler_events_total",
                      "Straggler flag events by rank and phase.",
                      labelnames=("rank", "phase"))
_AGG_STEPS = counter("cluster_aggregated_steps_total",
                     "Steps rank 0 fully aggregated across ranks.")


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank-with-interpolation percentile over a sorted list."""
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return float(sorted_vals[0])
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return float(sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac)


def _dist(vals: Sequence[float]) -> Dict[str, float]:
    s = sorted(float(v) for v in vals)
    return {
        "min": s[0] if s else 0.0,
        "median": _percentile(s, 0.5),
        "max": s[-1] if s else 0.0,
        "p95": _percentile(s, 0.95),
    }


class ClusterTelemetry:
    """Per-rank publisher + (on rank 0) cross-rank aggregator.

    Args:
        store: TCPStore-compatible object (set/get/delete). Blocking `get`
            must accept the key's eventual arrival; InProcStore and the
            native TCPStore both qualify.
        rank / world_size: this process's coordinates.
        k / m: straggler threshold and persistence; None reads the
            FLAGS_straggler_k / FLAGS_straggler_m knobs.
        timeout_s: per-rank record wait during aggregation — a rank silent
            for this long turns into a `cluster_timeout` event, not a hang.
    """

    def __init__(self, store, rank: int, world_size: int, *,
                 k: Optional[float] = None, m: Optional[int] = None,
                 prefix: str = "/pt/cluster", timeout_s: float = 60.0,
                 phases: Sequence[str] = telemetry.PHASES):
        self.store = store
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.k = float(get_flag("straggler_k") if k is None else k)
        self.m = max(int(get_flag("straggler_m") if m is None else m), 1)
        self.prefix = prefix.rstrip("/")
        self.timeout_s = float(timeout_s)
        self.phases = tuple(phases)
        self._lock = threading.Lock()
        # rank -> phase -> consecutive over-threshold steps
        self._streaks: Dict[int, Dict[str, int]] = {}
        self._flagged: Dict[int, Dict[str, int]] = {}  # rank->phase->step
        self.straggler_events: List[Dict[str, Any]] = []
        self.aggregates: List[Dict[str, Any]] = []  # bounded below
        self._max_kept = 64

    # -- publishing (every rank) -------------------------------------------
    def _key(self, step: int, rank: int) -> str:
        return f"{self.prefix}/{int(step)}/{int(rank)}"

    def slim(self, record: Dict[str, Any]) -> Dict[str, Any]:
        out = {f: record[f] for f in _SLIM_FIELDS if record.get(f) is not None}
        out["rank"] = self.rank
        out["phases"] = {p: float(record.get("phases", {}).get(p, 0.0))
                         for p in self.phases}
        return out

    def publish(self, record: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Publish this rank's record for its step; on rank 0 additionally
        collect all ranks and aggregate. Returns the aggregate (rank 0)."""
        step = int(record["step"])
        self.store.set(self._key(step, self.rank),
                       json.dumps(self.slim(record)))
        if self.rank == 0:
            return self.aggregate(step)
        return None

    # -- aggregation (rank 0) ----------------------------------------------
    def _collect(self, step: int) -> List[Dict[str, Any]]:
        recs = []
        for r in range(self.world_size):
            key = self._key(step, r)
            try:
                raw = self.store.get(key)
            except Exception as e:  # timeout / dead rank: event, not a hang
                telemetry.get_telemetry().event(
                    "cluster_timeout", step=step, rank=r,
                    error=f"{type(e).__name__}: {e}")
                continue
            if raw is None:
                continue
            try:
                recs.append(json.loads(raw))
            except (ValueError, TypeError):
                continue
            # aggregated: the store should not accumulate history
            try:
                self.store.delete(key)
            except Exception:  # noqa: BLE001 — GC is best-effort
                pass
        return recs

    def aggregate(self, step: int) -> Optional[Dict[str, Any]]:
        recs = self._collect(step)
        if not recs:
            return None
        agg: Dict[str, Any] = {"kind": "cluster_step", "ts": time.time(),
                               "step": int(step), "ranks": len(recs),
                               "phases": {}}
        for p in self.phases:
            vals = [r["phases"].get(p, 0.0) for r in recs]
            d = _dist(vals)
            agg["phases"][p] = {k: round(v, 6) for k, v in d.items()}
            for stat in _STATS:
                _PHASE_G.set(d[stat], phase=p, stat=stat)
        losses = [r["loss"] for r in recs if r.get("loss") is not None]
        if losses:
            d = _dist(losses)
            agg["loss"] = {k: round(v, 6) for k, v in d.items()}
            for stat in _STATS:
                _LOSS_G.set(d[stat], stat=stat)
        walls = [r["step_wall_s"] for r in recs
                 if r.get("step_wall_s") is not None]
        if walls:
            d = _dist(walls)
            agg["step_wall_s"] = {k: round(v, 6) for k, v in d.items()}
            for stat in _STATS:
                _WALL_G.set(d[stat], stat=stat)
        tps = sum(r.get("tokens_per_s") or 0.0 for r in recs)
        sps = sum(r.get("samples_per_s") or 0.0 for r in recs)
        if tps:
            agg["tokens_per_s_total"] = round(tps, 3)
            _TPS_G.set(tps)
        if sps:
            agg["samples_per_s_total"] = round(sps, 3)
            _SPS_G.set(sps)
        agg["stragglers"] = self._detect_stragglers(step, recs)
        _AGG_STEPS.inc()
        telemetry.get_telemetry().event(
            "cluster_step", **{k: v for k, v in agg.items()
                               if k not in ("kind", "ts")})
        with self._lock:
            self.aggregates.append(agg)
            del self.aggregates[:-self._max_kept]
        flight_recorder.set_cluster_snapshot(self.snapshot())
        return agg

    def _detect_stragglers(self, step: int,
                           recs: List[Dict[str, Any]]) -> List[Dict]:
        flagged = []
        for p in _STRAGGLER_PHASES:
            if p not in self.phases:
                continue
            vals = {int(r["rank"]): float(r["phases"].get(p, 0.0))
                    for r in recs}
            med = _percentile(sorted(vals.values()), 0.5)
            if med <= 0.0:
                continue  # phase not measured this step (e.g. overlapped
                # reduce is honestly 0.0) — no meaningful ratio exists
            for rank, v in vals.items():
                streaks = self._streaks.setdefault(rank, {})
                if v > self.k * med:
                    streaks[p] = streaks.get(p, 0) + 1
                else:
                    streaks[p] = 0
                    continue
                if streaks[p] >= self.m:
                    ev = {
                        "rank": rank, "phase": p, "step": int(step),
                        "value_s": round(v, 6), "median_s": round(med, 6),
                        "ratio": round(v / med, 3), "streak": streaks[p],
                        "k": self.k, "m": self.m,
                    }
                    flagged.append(ev)
                    first = streaks[p] == self.m  # rising edge
                    self._flagged.setdefault(rank, {})[p] = int(step)
                    if first:
                        self.straggler_events.append(
                            dict(ev, ts=time.time()))
                        del self.straggler_events[:-self._max_kept]
                        _STRAGGLERS.inc(rank=str(rank), phase=p)
                        telemetry.get_telemetry().event("straggler", **ev)
        return flagged

    # -- introspection ------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Current cluster view — embedded into flight-recorder dumps."""
        with self._lock:
            last = self.aggregates[-1] if self.aggregates else None
            return {
                "world_size": self.world_size,
                "k": self.k, "m": self.m,
                "last_aggregate": last,
                "active_streaks": {
                    str(r): {p: s for p, s in ph.items() if s}
                    for r, ph in self._streaks.items()
                    if any(ph.values())},
                "flagged": {str(r): dict(ph)
                            for r, ph in self._flagged.items()},
                "straggler_events": list(self.straggler_events[-8:]),
            }


def from_env(**kwargs) -> ClusterTelemetry:
    """ClusterTelemetry over the process-group store and this process's
    rank/world (distributed/env.py)."""
    from ..distributed import env as _env

    world = _env.get_world_size()
    return ClusterTelemetry(_env.get_store(world), _env.get_rank(), world,
                            **kwargs)
