"""Unified span tracing across subsystems.

One process-wide bounded span ring that every runtime component writes
through `span("name")`: TrainStep dispatch, DevicePrefetcher waits,
grad-bucket construction, CheckpointManager save/commit, collective init,
and `profiler.RecordEvent`'s pure-Python fallback. Three consumers:

  * the native HostTracer (native/src/tracer.cc) — when the C++ tracer is
    available AND actively recording, spans are mirrored through
    trace_push/trace_pop so they land in the existing chrome-trace merge
    (profiler/xplane.py) exactly like hand-annotated RecordEvents;
  * the profiler's pure-Python fallback — when the native library is absent,
    `Profiler` collects spans from THIS ring between start/stop (the
    fallback RecordEvent's docstring promised and r6–r8 silently dropped);
  * the crash flight recorder — `tail(n)` returns the most recent spans for
    post-mortem dumps regardless of any profiler session.

Clock: time.monotonic_ns(), the same steady clock family as the native
tracer's now_ns, so merged timelines share an axis.

Recording is gated: a span records when FLAGS_metrics is on, a profiler
fallback session is open, or the native tracer is live — otherwise
`span()` is a two-attribute-check no-op (near-zero overhead off).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .registry import metrics_enabled

_MAX_SPANS = 65536

_lock = threading.Lock()
_ring: deque = deque(maxlen=_MAX_SPANS)
_seq = 0
_session_depth = 0  # profiler fallback sessions currently open


def session(on: bool) -> None:
    """Open/close a pure-Python profiler recording session (profiler/)."""
    global _session_depth
    with _lock:
        _session_depth = max(_session_depth + (1 if on else -1), 0)


def _native_live() -> bool:
    try:
        from .. import native

        return native.available() and native.trace_enabled()
    except Exception:
        return False


def enabled() -> bool:
    return _session_depth > 0 or metrics_enabled() or _native_live()


def mark() -> int:
    """Sequence watermark; `since(mark())` later returns spans recorded
    after this point (profiler fallback session collection)."""
    with _lock:
        return _seq


def record_span(name: str, begin_ns: int, end_ns: int, cat: str = "span",
                args: Optional[Dict] = None) -> None:
    """Append one completed span to the ring (also the RecordEvent-fallback
    entry point). Caller supplies monotonic_ns timestamps."""
    global _seq
    span_d = {
        "name": str(name),
        "begin_ns": int(begin_ns),
        "end_ns": int(end_ns),
        "tid": threading.get_ident() & 0xFFFF,
        "cat": cat,
    }
    if args:
        span_d["args"] = args
    with _lock:
        _seq += 1
        _ring.append((_seq, span_d))


def since(watermark: int) -> List[Dict]:
    with _lock:
        return [s for q, s in _ring if q > watermark]


def tail(n: int = 200) -> List[Dict]:
    with _lock:
        items = list(_ring)[-int(n):]
    return [s for _, s in items]


def clear() -> None:
    global _seq
    with _lock:
        _ring.clear()
        _seq = 0


class span:
    """Context manager recording one span into the unified ring, mirrored
    to the native tracer when it is live.

        with span("ckpt.commit", cat="io", args={"step": 7}):
            ...
    """

    __slots__ = ("name", "cat", "args", "_t0", "_native", "_on")

    def __init__(self, name: str, cat: str = "span",
                 args: Optional[Dict] = None):
        self.name = name
        self.cat = cat
        self.args = args
        self._t0 = 0
        self._native = False
        self._on = False

    def __enter__(self):
        self._on = enabled()
        if self._on:
            self._t0 = time.monotonic_ns()
            if _native_live():
                try:
                    from .. import native

                    native.trace_push(self.name)
                    self._native = True
                except Exception:
                    self._native = False
        return self

    def __exit__(self, *exc):
        if self._on:
            if self._native:
                try:
                    from .. import native

                    native.trace_pop()
                except Exception:
                    pass
            record_span(self.name, self._t0, time.monotonic_ns(),
                        cat=self.cat, args=self.args)
        return False
