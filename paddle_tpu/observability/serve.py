"""Live scrape endpoint: /metrics (Prometheus text) + /healthz (JSON).

The textfile sink covers node-exporter setups; a real cluster scrapes HTTP.
One stdlib ThreadingHTTPServer on FLAGS_metrics_port (0 = disabled; an
ephemeral port is picked when constructed with port=0 explicitly, for
tests), serving:

  * GET /metrics  — the registry rendered through sinks.prometheus_text,
                    always fresh (memory gauges refreshed per scrape);
  * GET /healthz  — {ok, status, step, last_step_age_s, anomalies_recent,
                    stragglers} with HTTP 200 when healthy and 503 when the
                    run is stale (no step for `stale_after_s`) or anomalous
                    in the last few minutes — load-balancer semantics, body
                    says why.

The server thread is a daemon reading shared singletons; it holds no lock
while rendering beyond the registry's own per-metric locks, so scraping
cannot stall a training step.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from . import memory, sinks, telemetry
from .registry import default_registry
from ..core.flags import define_flag, get_flag

define_flag(
    "metrics_port", 0,
    "Serve /metrics (Prometheus text) and /healthz (JSON run health) on "
    "this port from inside the training process; 0 disables the endpoint. "
    "Needs FLAGS_metrics=on to have anything to say.")

STALE_AFTER_S = 300.0  # healthz: no step for this long => status "stale"
ANOMALY_RECENT_S = 300.0  # healthz: anomalies within this window count


def metrics_body() -> bytes:
    """The GET /metrics response body: the whole registry as Prometheus
    text, memory gauges refreshed per scrape. Shared by this server and
    the serving front end (serving/server.py) so both scrape surfaces
    render identically."""
    try:
        memory.update_memory_gauges()  # fresh HBM per scrape
    except Exception:  # noqa: BLE001
        pass
    return sinks.prometheus_text(default_registry()).encode()


def health_snapshot(stale_after_s: float = STALE_AFTER_S) -> Dict[str, Any]:
    """The /healthz body, also usable directly (obsbench, tests)."""
    now = time.time()
    tele = telemetry.get_telemetry()
    last = dict(getattr(tele, "_last", {}) or {})
    out: Dict[str, Any] = {
        "status": "ok",
        "ok": True,
        "step": last.get("step"),
        "last_step_age_s": None,
        "records_emitted": tele.records_emitted,
    }
    ts = last.get("ts")
    if ts:
        out["last_step_age_s"] = round(now - float(ts), 3)
        if out["last_step_age_s"] > float(stale_after_s):
            out["status"], out["ok"] = "stale", False
    elif tele.records_emitted == 0 and not last:
        out["status"] = "idle"  # serving before the first step is not failure
    eng = _engine()
    recent = []
    if eng is not None:
        recent = [a for a in eng.recent()
                  if now - float(a.get("ts", 0)) <= ANOMALY_RECENT_S]
    out["anomalies_recent"] = len(recent)
    if recent:
        out["status"], out["ok"] = "anomalous", False
        out["last_anomaly"] = {k: v for k, v in recent[-1].items()
                               if k in ("kind", "step", "value")}
    from . import flight_recorder as _fr

    snap = _fr.cluster_snapshot()
    if snap:
        out["stragglers"] = snap.get("flagged", {})
    return out


_engine_ref: Optional[Any] = None


def _engine():
    return _engine_ref


def set_health_engine(engine) -> None:
    """Point /healthz at the live AnomalyEngine (ResilientTrainer does)."""
    global _engine_ref
    _engine_ref = engine


class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle_tpu_metrics/1.0"

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                self._reply(200, metrics_body(),
                            "text/plain; version=0.0.4; charset=utf-8")
            elif path in ("/healthz", "/health"):
                snap = health_snapshot()
                body = json.dumps(snap).encode()
                self._reply(200 if snap["ok"] or snap["status"] == "idle"
                            else 503, body, "application/json")
            else:
                self._reply(404, b'{"error": "not found"}',
                            "application/json")
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _reply(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # scrapes must not spam stderr
        pass


class MetricsServer:
    """Owns the HTTP server + its daemon thread."""

    def __init__(self, port: int = 0, host: str = "0.0.0.0"):
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.port = int(self._httpd.server_address[1])
        self.host = host
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.25},
            name="metrics-http", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __repr__(self):  # pragma: no cover
        return f"MetricsServer(port={self.port})"


_server: Optional[MetricsServer] = None
_server_lock = threading.Lock()


def start_metrics_server(port: Optional[int] = None) -> MetricsServer:
    """Start (or return) the process-wide server. port=None reads
    FLAGS_metrics_port; port=0 binds an ephemeral port (tests)."""
    global _server
    with _server_lock:
        if _server is not None:
            return _server
        if port is None:
            port = int(get_flag("metrics_port"))
        _server = MetricsServer(port)
        return _server


def maybe_start_from_flags() -> Optional[MetricsServer]:
    """FLAGS_metrics_port > 0 => the server; else None. Safe to call every
    run start — idempotent, and bind errors degrade to a warning event, not
    a dead training job."""
    p = int(get_flag("metrics_port"))
    if p <= 0:
        return None
    try:
        return start_metrics_server(p)
    except OSError as e:
        telemetry.get_telemetry().event(
            "metrics_server_error", port=p, error=f"{type(e).__name__}: {e}")
        return None


def reset() -> None:
    """Stop and drop the server + health engine (tests / reset_all)."""
    global _server, _engine_ref
    with _server_lock:
        if _server is not None:
            try:
                _server.stop()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
            _server = None
    _engine_ref = None
