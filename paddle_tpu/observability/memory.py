"""Memory observability: per-device HBM gauges + per-executable XLA cost
accounting.

Two vantage points, both production signals in the Gemma-on-TPU report
(arXiv 2605.25645 — per-device HBM and compiled-memory budgets are watched
live, not post-mortem):

  * runtime — `jax.Device.memory_stats()` per local device: live bytes,
    peak bytes, allocator limit. TPU/GPU runtimes report these; the CPU
    backend returns None, so the host process's RSS (live, from
    /proc/self/statm) and peak RSS (ru_maxrss) stand in — the gauges always
    exist, whatever the backend, so dashboards and tests are
    backend-agnostic. "Are we about to OOM" is
    `device_memory_bytes{kind="bytes_in_use"}` vs `{kind="bytes_limit"}`.
  * compile time — every AOT-compiled TrainStep executable reports its XLA
    cost analysis (flops, bytes accessed) and memory analysis (argument /
    output / temp / generated-code bytes). jit.trainer calls
    `note_executable` right after `.compile()`, so a recompile that doubles
    temp memory shows up as a gauge step BEFORE the OOM, and the telemetry
    event log records which compile did it.

`tools/memwatch.py` renders both into one report.
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

from .registry import gauge

_DEV_G = gauge("device_memory_bytes",
               "Per-device allocator stats (live/peak/limit bytes) from "
               "jax.Device.memory_stats().",
               labelnames=("device", "kind"))
_HOST_G = gauge("host_memory_bytes",
                "Host process memory (rss = live, peak_rss = high water).",
                labelnames=("kind",))
_EXE_B = gauge("executable_bytes",
               "Compiled-executable memory budget from XLA memory analysis.",
               labelnames=("what", "kind"))
_EXE_F = gauge("executable_flops",
               "FLOPs per invocation from XLA cost analysis.",
               labelnames=("what",))
_EXE_BA = gauge("executable_bytes_accessed",
                "Bytes accessed per invocation from XLA cost analysis.",
                labelnames=("what",))

# memory_stats() key -> our stable gauge label (runtimes vary slightly)
_STAT_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
              "largest_alloc_size", "pool_bytes")
_MEM_KINDS = ("argument", "output", "temp", "alias", "generated_code")


def host_memory_bytes() -> Dict[str, int]:
    """Live RSS + peak RSS of this process, portable-ish (Linux /proc for
    live, getrusage for peak; zeros where unsupported)."""
    out = {"rss": 0, "peak_rss": 0}
    try:
        with open("/proc/self/statm") as f:
            out["rss"] = int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        # ru_maxrss is KiB on Linux, bytes on macOS
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        out["peak_rss"] = peak * (1 if peak > 1 << 32 else 1024)
    except Exception:  # noqa: BLE001 — no resource module
        pass
    return out


def device_memory_stats() -> List[Dict[str, Any]]:
    """One entry per local device: raw memory_stats() (may be None on CPU)
    plus identifying fields."""
    out = []
    try:
        import jax

        for d in jax.local_devices():
            try:
                stats = d.memory_stats()
            except Exception:  # noqa: BLE001 — backend without support
                stats = None
            out.append({
                "device": str(d.id),
                "platform": getattr(d, "platform", "?"),
                "kind": getattr(d, "device_kind", "?"),
                "stats": stats,
            })
    except Exception:  # noqa: BLE001 — jax not importable in odd contexts
        pass
    return out


def update_memory_gauges() -> Dict[str, Any]:
    """Refresh `device_memory_bytes` / `host_memory_bytes` gauges; returns
    the summary dict (what memwatch prints). Cheap: one C call per device
    plus two procfs reads."""
    summary: Dict[str, Any] = {"ts": time.time(), "devices": [], "host": {}}
    for entry in device_memory_stats():
        stats = entry["stats"] or {}
        row = {"device": entry["device"], "platform": entry["platform"],
               "kind": entry["kind"]}
        for key in _STAT_KEYS:
            if key in stats:
                v = int(stats[key])
                row[key] = v
                _DEV_G.set(v, device=entry["device"], kind=key)
        summary["devices"].append(row)
    host = host_memory_bytes()
    for k, v in host.items():
        _HOST_G.set(v, kind=k)
    summary["host"] = host
    return summary


def _cost_dict(compiled) -> Dict[str, float]:
    """Normalize compiled.cost_analysis() across jax versions (dict, or a
    one-element list of dicts) down to the two portable figures."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — analysis unsupported on backend
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    out = {}
    for key in ("flops", "bytes accessed"):
        try:
            v = float(ca.get(key, -1.0))
        except (TypeError, ValueError):
            continue
        if v >= 0:
            out[key.replace(" ", "_")] = v
    return out


def executable_analysis(compiled) -> Dict[str, Any]:
    """flops / bytes-accessed / memory budget of one compiled executable."""
    out: Dict[str, Any] = dict(_cost_dict(compiled))
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001
        ma = None
    if ma is not None:
        for kind in _MEM_KINDS:
            v = getattr(ma, f"{kind}_size_in_bytes", None)
            if v is not None:
                out[f"{kind}_bytes"] = int(v)
        total = sum(out.get(f"{k}_bytes", 0)
                    for k in ("argument", "output", "temp"))
        if total:
            out["total_bytes"] = total
    return out


def note_executable(what: str, compiled) -> Dict[str, Any]:
    """Record one compiled executable's budget into gauges + the event log.
    Called by jit.trainer right after AOT compile; never raises (a cost
    analysis must not break a compile that already succeeded)."""
    try:
        info = executable_analysis(compiled)
    except Exception:  # noqa: BLE001
        return {}
    if not info:
        return {}
    for kind in _MEM_KINDS + ("total",):
        v = info.get(f"{kind}_bytes")
        if v is not None:
            _EXE_B.set(v, what=what, kind=kind)
    if "flops" in info:
        _EXE_F.set(info["flops"], what=what)
    if "bytes_accessed" in info:
        _EXE_BA.set(info["bytes_accessed"], what=what)
    from . import telemetry  # late: telemetry refreshes gauges through us

    telemetry.get_telemetry().event("executable", what=what, **info)
    return info


def memory_report() -> Dict[str, Any]:
    """The full memory picture (tools/memwatch.py): device + host gauges
    refreshed now, plus every executable budget currently registered."""
    report = update_memory_gauges()
    exes: Dict[str, Dict[str, float]] = {}
    for metric, key_label in ((_EXE_B, "kind"), ):
        for labels, v in metric.samples():
            exes.setdefault(labels["what"], {})[labels[key_label]] = v
    for labels, v in _EXE_F.samples():
        exes.setdefault(labels["what"], {})["flops"] = v
    for labels, v in _EXE_BA.samples():
        exes.setdefault(labels["what"], {})["bytes_accessed"] = v
    report["executables"] = exes
    return report
