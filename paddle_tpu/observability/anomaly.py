"""Online anomaly detection over per-step telemetry.

Rolling-window detectors consume the SAME step records telemetry already
assembles (no second instrumentation path) and turn "the run started
degrading at step 4017" from a post-hoc grep into a live, structured
`anomaly` event — with the flight recorder dumped at the moment of
detection, the anomaly attached, so the black box covers the steps that
LED INTO the regression (the Gemma-on-TPU production stance: step-time and
loss distributions are first-class signals, not log archaeology).

Five detectors, all O(window) per step, all host-side (nothing touches
the compiled program):

  * loss_spike        — loss z-score over a rolling window (robust floor on
                        sigma so flat-loss phases don't divide by ~0);
  * grad_norm_spike   — same statistic over the pre-clip global grad-norm;
  * step_time_regression — step wall time > ratio x rolling median for
                        `patience` consecutive steps (excludes compile
                        steps via the record's own compile events);
  * throughput_collapse — tokens/s (or samples/s) < collapse_frac x rolling
                        median for `patience` consecutive steps;
  * compile_cache_collapse — the compile-cache miss counter moving on
                        `patience` consecutive steps: a recompile storm
                        (hit-rate collapse) in steady state.

Detectors only fire once warm (min_points) and re-arm after `cooldown`
steps, so one bad phase produces one anomaly + one dump, not a dump per
step. Everything is inert unless FLAGS_metrics=on AND FLAGS_anomaly=on
(ResilientTrainer checks both before constructing an engine).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from . import flight_recorder, telemetry
from .registry import counter, metrics_enabled
from ..core.flags import define_flag, get_flag

define_flag(
    "anomaly", "off",
    "Online anomaly engine over per-step telemetry: 'on' runs the rolling "
    "detectors (loss/grad-norm spike, step-time regression, throughput "
    "collapse, compile-cache collapse) inside ResilientTrainer and dumps "
    "the flight recorder when one fires. Needs FLAGS_metrics=on.")

_ANOMALIES = counter("anomaly_events_total",
                     "Anomalies detected by the online engine, by kind.",
                     labelnames=("kind",))

_TRUE = ("1", "on", "true", "yes")


def anomaly_enabled() -> bool:
    return metrics_enabled() and str(get_flag("anomaly")).lower() in _TRUE


class RollingDetector:
    """Base: keeps a bounded window of a scalar field; subclasses decide."""

    kind = "anomaly"
    field = "loss"

    def __init__(self, window: int = 32, min_points: int = 8,
                 cooldown: int = 25):
        self.window = deque(maxlen=int(window))
        self.min_points = int(min_points)
        self.cooldown = int(cooldown)
        self._cooldown_until = -1

    def value(self, rec: Dict[str, Any]) -> Optional[float]:
        v = rec.get(self.field)
        try:
            return float(v) if v is not None else None
        except (TypeError, ValueError):
            return None

    def check(self, v: float, rec: Dict[str, Any]) -> Optional[Dict]:
        raise NotImplementedError

    def observe(self, rec: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        v = self.value(rec)
        if v is None:
            return None
        step = int(rec.get("step", -1))
        out = None
        if len(self.window) >= self.min_points and \
                step > self._cooldown_until:
            out = self.check(v, rec)
            if out is not None:
                self._cooldown_until = step + self.cooldown
                out.setdefault("kind", self.kind)
                out.setdefault("field", self.field)
                out["step"] = step
                out["value"] = round(v, 6)
        self.window.append(v)
        return out


class _ZSpike(RollingDetector):
    """value > mean + z*sigma AND > factor*mean: both a statistical outlier
    and materially larger (sigma floors keep flat phases from firing)."""

    z = 6.0
    factor = 1.5

    def check(self, v, rec):
        vals = list(self.window)
        n = len(vals)
        mean = sum(vals) / n
        var = sum((x - mean) ** 2 for x in vals) / n
        sigma = max(var ** 0.5, abs(mean) * 0.02, 1e-12)
        if v > mean + self.z * sigma and v > self.factor * abs(mean):
            return {"mean": round(mean, 6), "sigma": round(sigma, 6),
                    "zscore": round((v - mean) / sigma, 3)}
        return None


class LossSpike(_ZSpike):
    kind = "loss_spike"
    field = "loss"


class GradNormSpike(_ZSpike):
    kind = "grad_norm_spike"
    field = "grad_norm"


class _SustainedRatio(RollingDetector):
    """value vs rolling-median ratio crossing a bound for `patience`
    consecutive steps (single hiccups — a GC pause, one slow batch — are
    not regressions)."""

    ratio = 2.0
    patience = 3
    direction = "above"  # or "below"

    def __init__(self, window: int = 32, min_points: int = 8,
                 cooldown: int = 25, patience: Optional[int] = None):
        super().__init__(window, min_points, cooldown)
        if patience is not None:
            self.patience = int(patience)
        self._streak = 0

    def _median(self) -> float:
        s = sorted(self.window)
        n = len(s)
        return (s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2.0)

    def check(self, v, rec):
        med = self._median()
        if med <= 0:
            return None
        r = v / med
        bad = r > self.ratio if self.direction == "above" \
            else r < self.ratio
        if not bad:
            self._streak = 0
            return None
        self._streak += 1
        if self._streak < self.patience:
            return None
        self._streak = 0
        return {"median": round(med, 6), "ratio": round(r, 3),
                "patience": self.patience}


class StepTimeRegression(_SustainedRatio):
    kind = "step_time_regression"
    field = "step_wall_s"
    ratio = 2.0
    direction = "above"


class ThroughputCollapse(_SustainedRatio):
    kind = "throughput_collapse"
    field = "tokens_per_s"
    ratio = 0.5
    direction = "below"

    def value(self, rec):
        v = rec.get("tokens_per_s", rec.get("samples_per_s"))
        try:
            return float(v) if v is not None else None
        except (TypeError, ValueError):
            return None


class CompileCacheCollapse(RollingDetector):
    """Compile-cache hit-rate collapse = a recompile storm: the cumulative
    miss counter advancing on `patience` consecutive steps. In steady state
    no step compiles at all, so ANY sustained miss motion is anomalous."""

    kind = "compile_cache_collapse"
    field = "compile_cache"
    patience = 3

    def __init__(self, window: int = 32, min_points: int = 2,
                 cooldown: int = 25, patience: int = 3):
        super().__init__(window, min_points, cooldown)
        self.patience = int(patience)
        self._last_misses: Optional[float] = None
        self._streak = 0

    def value(self, rec):
        cc = rec.get("compile_cache")
        if not isinstance(cc, dict):
            return None
        try:
            return float(cc.get("misses", 0))
        except (TypeError, ValueError):
            return None

    def check(self, v, rec):
        last, self._last_misses = self._last_misses, v
        if last is None or v <= last:
            self._streak = 0
            return None
        self._streak += 1
        if self._streak < self.patience:
            return None
        self._streak = 0
        hits = 0.0
        cc = rec.get("compile_cache") or {}
        try:
            hits = float(cc.get("hits", 0))
        except (TypeError, ValueError):
            pass
        total = hits + v
        return {"misses": v, "patience": self.patience,
                "hit_rate": round(hits / total, 4) if total else 0.0}

    def observe(self, rec):  # misses delta needs every step, warm or not
        v = self.value(rec)
        if v is None:
            return None
        step = int(rec.get("step", -1))
        if self._last_misses is None:
            self._last_misses = v
            self.window.append(v)
            return None
        out = None
        if step > self._cooldown_until:
            out = self.check(v, rec)
            if out is not None:
                self._cooldown_until = step + self.cooldown
                out.setdefault("kind", self.kind)
                out["step"] = step
                out["value"] = v
        else:
            self._last_misses = v
        self.window.append(v)
        return out


def default_detectors(**kw) -> List[RollingDetector]:
    return [LossSpike(**kw), GradNormSpike(**kw), StepTimeRegression(**kw),
            ThroughputCollapse(**kw), CompileCacheCollapse()]


# -- serving detectors (r16) -------------------------------------------------
# Same rolling-window machinery over the serving engine's per-tick records
# (serving/observability.py assembles them): latency/goodput/cache-hit
# regressions relative to the run's own recent history, plus a hard
# invariant check on the block allocator. Record fields are only present
# when the tick had the signal (no TTFT field on a tick that admitted
# nothing), which RollingDetector already tolerates (value() -> None).

class TTFTRegression(_SustainedRatio):
    """Mean TTFT of the tick's admissions > ratio x rolling median for
    `patience` consecutive ticks-with-admissions: the latency-collapse
    signal an SLO-aware router sheds on."""

    kind = "ttft_regression"
    field = "ttft_s"
    ratio = 3.0
    direction = "above"


class GoodputCollapse(_SustainedRatio):
    """Windowed decoded tokens/s < ratio x rolling median while work is
    queued or running — the serving analog of ThroughputCollapse."""

    kind = "goodput_collapse"
    field = "goodput_tokens_per_s"
    ratio = 0.5
    direction = "below"

    def value(self, rec):
        v = super().value(rec)
        if v is None:
            return None
        # idle engine (nothing to decode) is not a collapse
        if not (rec.get("running") or rec.get("waiting")):
            return None
        return v


class CacheHitCollapse(_SustainedRatio):
    """Rolling prefix-cache hit rate < ratio x its own median: the cache
    stopped matching (eviction storm, workload shift, or a chain-hash
    regression) on a workload that used to hit."""

    kind = "cache_hit_collapse"
    field = "prefix_hit_rate"
    ratio = 0.5
    direction = "below"


class KVConservationBreach(RollingDetector):
    """Block-allocator conservation law (ref + evictable + free ==
    num_blocks - 1) violated: not statistical — fires on the first breached
    tick (leak or double-free; KV corruption follows)."""

    kind = "kv_conservation_breach"
    field = "kv_conservation_breach"

    def __init__(self, window: int = 32, cooldown: int = 25):
        super().__init__(window, min_points=0, cooldown=cooldown)

    def check(self, v, rec):
        return {} if v > 0 else None


def serving_default_detectors(**kw) -> List[RollingDetector]:
    return [TTFTRegression(**kw), GoodputCollapse(**kw),
            CacheHitCollapse(**kw), KVConservationBreach()]


# -- fleet detectors (r19) ---------------------------------------------------
# Router-level pathologies over the FleetObservability per-poll tick
# records (serving/fleet_observability.py assembles them). These are
# absolute-threshold detectors, not ratio-vs-median ones: the healthy
# baseline for hedges, re-dispatches and breaker transitions is ZERO, so
# a median-relative detector could never warm up into firing.

class _SustainedThreshold(RollingDetector):
    """value crossing an absolute bound for `patience` consecutive
    records. min_points defaults to 0 — an absolute bound needs no
    warm-up history, and record fields are already windowed rates."""

    bound = 1.0
    patience = 1
    direction = "above"  # or "below"

    def __init__(self, window: int = 32, min_points: int = 0,
                 cooldown: int = 25, patience: Optional[int] = None,
                 bound: Optional[float] = None):
        super().__init__(window, min_points, cooldown)
        if patience is not None:
            self.patience = int(patience)
        if bound is not None:
            self.bound = float(bound)
        self._streak = 0

    def check(self, v, rec):
        bad = v > self.bound if self.direction == "above" \
            else v < self.bound
        if not bad:
            self._streak = 0
            return None
        self._streak += 1
        if self._streak < self.patience:
            return None
        self._streak = 0
        return {"bound": self.bound, "patience": self.patience}


class HedgeRateSpike(_SustainedThreshold):
    """Hedges fired / requests placed over the tick window past the
    bound: a hedge storm (systemically slow replicas, or a hedge
    deadline tuned below honest TTFT) — every hedge doubles load."""

    kind = "hedge_rate_spike"
    field = "hedge_rate"
    bound = 0.3
    patience = 1


class RedispatchStorm(_SustainedThreshold):
    """Re-dispatches / placements over the tick window past the bound:
    replicas are dying (or being declared dead) faster than a one-off
    failure — lease TTL vs heartbeat misconfiguration, crash loop."""

    kind = "redispatch_storm"
    field = "redispatch_rate"
    bound = 0.3
    patience = 1


class BreakerFlap(_SustainedThreshold):
    """Circuit-breaker oscillation: max per-replica breaker transitions
    inside the detector window >= bound (two full open->half_open->
    open cycles). A flapping breaker means probes keep succeeding into
    a replica that keeps failing real traffic."""

    kind = "breaker_flap"
    field = "breaker_flaps"
    bound = 4.0
    patience = 1

    def check(self, v, rec):
        # >= semantics: four transitions in-window IS two flap cycles
        if v < self.bound:
            self._streak = 0
            return None
        return {"bound": self.bound, "patience": self.patience}


class ReplicaSkew(_SustainedThreshold):
    """Sustained cross-replica p95-TTFT skew (max replica p95 / min
    replica p95) past the bound: one replica is systematically slower —
    thermal throttle, noisy neighbor, or a cache gone cold."""

    kind = "replica_skew"
    field = "ttft_skew"
    bound = 3.0
    patience = 3


def fleet_default_detectors(**kw) -> List[RollingDetector]:
    return [HedgeRateSpike(**kw), RedispatchStorm(**kw),
            BreakerFlap(**kw), ReplicaSkew(**kw)]


class AnomalyEngine:
    """Feeds step records through every detector; on a hit emits the
    structured `anomaly` event (JSONL + Prometheus counter + flight-recorder
    note) and — unless disarmed — dumps the flight recorder with the anomaly
    attached. One engine per training loop; thread-safe for the
    serve.py health endpoint reading `recent()`."""

    def __init__(self, detectors: Optional[List[RollingDetector]] = None,
                 *, dump: bool = True, dump_cooldown_steps: int = 50):
        self.detectors = (default_detectors() if detectors is None
                          else list(detectors))
        self.dump = bool(dump)
        self.dump_cooldown_steps = int(dump_cooldown_steps)
        self._dump_armed_at = -1
        self._lock = threading.Lock()
        self._recent: deque = deque(maxlen=64)
        self.dumps: List[str] = []

    def observe(self, record: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Run every detector over one step record; returns anomalies."""
        found = []
        for d in self.detectors:
            try:
                ev = d.observe(record)
            except Exception:  # noqa: BLE001 — detection never kills a run
                continue
            if ev is not None:
                found.append(ev)
        for ev in found:
            self._emit(ev)
        return found

    def _emit(self, ev: Dict[str, Any]) -> None:
        ev = dict(ev, ts=time.time())
        with self._lock:
            self._recent.append(ev)
        _ANOMALIES.inc(kind=ev["kind"])
        telemetry.get_telemetry().event(
            "anomaly", anomaly_kind=ev["kind"],
            **{k: v for k, v in ev.items() if k not in ("ts", "kind")})
        flight_recorder.note_anomaly(ev)
        step = int(ev.get("step", -1))
        if self.dump and step > self._dump_armed_at:
            self._dump_armed_at = step + self.dump_cooldown_steps
            try:
                path = flight_recorder.get_flight_recorder().dump(
                    f"anomaly_{ev['kind']}", extra={"anomaly": ev})
                self.dumps.append(path)
            except OSError:
                pass

    def recent(self, n: int = 16) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._recent)[-int(n):]


def from_flags(**kw) -> Optional[AnomalyEngine]:
    """An engine when FLAGS_metrics=on and FLAGS_anomaly=on, else None —
    the one-liner ResilientTrainer.run uses."""
    return AnomalyEngine(**kw) if anomaly_enabled() else None
