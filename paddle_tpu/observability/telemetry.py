"""Per-step training telemetry.

The runtime itself emits one record per optimizer step — loss, grad
global-norm, learning rate, throughput (samples/s, tokens/s), estimated
MFU, per-phase wall times (data / compute / reduce / save), and compile /
recompile events — so benches and dashboards read phases from the live run
instead of re-timing them externally (the T3 / Gemma-on-TPU accounting the
ISSUE cites; tools/stepbench.py consumes this).

Assembly protocol (who knows what, when):

  * the training loop times the DATA phase before the step and calls
    `pre_phase("data", dt)` — it lands on the NEXT record;
  * jit.TrainStep calls `on_step(core)` with loss / grad-norm / lr /
    compute time measured around its own dispatch; this STAGES the record
    (and pushes it, by reference, into the flight-recorder ring);
  * the loop times the SAVE phase after the step and calls
    `post_phase("save", dt)` — merged into the staged record;
  * the NEXT `on_step` (or `finalize()`) flushes the completed record to
    the JSONL event log, so late phases are never lost to the sink.

On the single-compiled-program path the gradient all-reduce is fused into
the step executable (XLA overlaps it with the backward — see
distributed/grad_buckets.py and distributed/overlap.py), so no
host-observable reduce wait exists. The `reduce` phase is instead the comm
cost jit.TrainStep ATTRIBUTES from inside the step: a standalone probe of
the step's own reduction schedule, carved out of `compute` so the phases
still sum to the measured step time; `reduce_overlapped` stays True to say
the time was attributed, not waited on.

Everything is inert while FLAGS_metrics is off: `enabled()` is one flag
read, and TrainStep checks it before building any record.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

from . import flight_recorder, sinks
from .registry import (counter, default_registry, gauge, histogram,
                       metrics_enabled)
from ..core.flags import get_flag

PHASES = ("data", "compute", "reduce", "save")

_STEPS = counter("training_steps_total", "Optimizer steps executed.")
_SKIPPED = counter("training_steps_skipped_total",
                   "Steps skipped by the NaN/Inf step-guard.")
_LOSS = gauge("training_loss", "Loss of the most recent step.")
_GNORM = gauge("training_grad_norm",
               "Gradient global-norm of the most recent step (pre-clip).")
_LR = gauge("training_lr", "Learning rate of the most recent step.")
_SPS = gauge("training_samples_per_second", "Recent-step throughput.")
_TPS = gauge("training_tokens_per_second", "Recent-step token throughput.")
_MFU = gauge("training_mfu",
             "Estimated model FLOPs utilization of the most recent step.")
_PHASE_S = counter("training_phase_seconds_total",
                   "Cumulative wall time per step phase.",
                   labelnames=("phase",))
_PHASE_H = histogram("training_phase_seconds",
                     "Per-step wall time by phase.", labelnames=("phase",))
_COMPILES = counter("training_compile_events_total",
                    "Compile/recompile events observed by telemetry.",
                    labelnames=("kind",))

_PROM_EVERY = 50  # steps between Prometheus textfile rewrites (finalize()
                  # always writes one, so short runs still get a file)
_MEM_EVERY = 20   # steps between device/host memory-gauge refreshes (one
                  # C call per device + two procfs reads; see memory.py)


def enabled() -> bool:
    return metrics_enabled()


def _peak_flops() -> float:
    """Peak FLOP/s for the MFU estimate: BENCH_PEAK_FLOPS env override, else
    the same defaults bench.py uses (v5e bf16 peak on an accelerator, a
    nominal 1e12 on CPU so smoke MFUs stay visibly tiny, not meaningless)."""
    env = os.environ.get("BENCH_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        import jax

        return 1e12 if jax.default_backend() == "cpu" else 197e12
    except Exception:
        return 1e12


class StepTelemetry:
    """Process-wide per-step record assembler (get_telemetry() singleton)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._staged: Optional[Dict[str, Any]] = None
        self._pending_phases: Dict[str, float] = {}
        self._last_step_t: Optional[float] = None
        self._jsonl: Optional[sinks.JsonlEventLog] = None
        self._jsonl_dir: Optional[str] = None
        self._flushed = 0
        self.records_emitted = 0
        self._totals: Dict[str, float] = {p: 0.0 for p in PHASES}
        self._last: Dict[str, Any] = {}

    # -- sinks -------------------------------------------------------------
    def _metrics_dir(self) -> str:
        return str(get_flag("metrics_dir") or "")

    def _sink(self) -> Optional[sinks.JsonlEventLog]:
        d = self._metrics_dir()
        if not d:
            return None
        if self._jsonl is None or self._jsonl_dir != d:
            if self._jsonl is not None:
                self._jsonl.close()
            self._jsonl = sinks.JsonlEventLog(
                os.path.join(d, sinks.EVENTS_FILENAME))
            self._jsonl_dir = d
        return self._jsonl

    def export_prometheus(self) -> Optional[str]:
        d = self._metrics_dir()
        if not d:
            return None
        return sinks.write_prometheus_textfile(
            os.path.join(d, sinks.PROM_FILENAME), default_registry())

    # -- phase accounting --------------------------------------------------
    def pre_phase(self, name: str, seconds: float) -> None:
        """Phase time measured BEFORE the step it belongs to (data wait)."""
        if not enabled():
            return
        with self._lock:
            self._pending_phases[name] = \
                self._pending_phases.get(name, 0.0) + float(seconds)

    def post_phase(self, name: str, seconds: float) -> None:
        """Phase time measured AFTER its step (checkpoint save): merged into
        the staged record so it ships with the right step."""
        if not enabled():
            return
        s = float(seconds)
        with self._lock:
            staged = self._staged
            if staged is not None:
                staged["phases"][name] = staged["phases"].get(name, 0.0) + s
        _PHASE_S.inc(s, phase=name)
        _PHASE_H.observe(s, phase=name)
        self._totals[name] = self._totals.get(name, 0.0) + s

    # -- per-step core (called by jit.TrainStep) ---------------------------
    def on_step(self, core: Dict[str, Any]) -> Dict[str, Any]:
        """Stage the record for one completed step; flush the previous one.
        `core` must carry: step, loss, lr, compute_s; optional grad_norm,
        skipped, samples, tokens, flops."""
        now = time.perf_counter()
        with self._lock:
            prev, self._staged = self._staged, None
            phases = {p: 0.0 for p in PHASES}
            phases.update(self._pending_phases)
            self._pending_phases = {}
        if prev is not None:
            self._write(prev)

        compute_s = float(core.get("compute_s", 0.0))
        # `reduce_s` is the comm time the step ATTRIBUTES out of its own
        # measured wall (jit.TrainStep's reduce probe): the collective is
        # fused into the step program, so it is carved out of compute rather
        # than added on top — phases keep summing to the measured step time
        reduce_s = min(float(core.get("reduce_s", 0.0) or 0.0), compute_s)
        if reduce_s > 0.0:
            phases["reduce"] = phases.get("reduce", 0.0) + reduce_s
            compute_s -= reduce_s
        phases["compute"] = phases.get("compute", 0.0) + compute_s
        # wall time step->step covers data+compute+save of the interleave;
        # throughput/MFU use it when available (first step: compute only)
        step_wall = (now - self._last_step_t) if self._last_step_t else \
            max(compute_s, 1e-9)
        self._last_step_t = now

        rec: Dict[str, Any] = {
            "kind": "step",
            "ts": time.time(),
            "step": int(core["step"]),
            "loss": _f(core.get("loss")),
            "grad_norm": _f(core.get("grad_norm")),
            "lr": _f(core.get("lr")),
            "skipped": bool(core.get("skipped", False)),
            "phases": phases,
            "step_wall_s": round(step_wall, 6),
            "reduce_overlapped": bool(core.get("reduce_overlapped", True)),
        }
        samples = core.get("samples")
        tokens = core.get("tokens")
        if samples:
            rec["samples"] = int(samples)
            rec["samples_per_s"] = round(samples / step_wall, 3)
        if tokens:
            rec["tokens"] = int(tokens)
            rec["tokens_per_s"] = round(tokens / step_wall, 3)
        flops = core.get("flops")
        if flops:
            rec["mfu"] = round(float(flops) / step_wall / _peak_flops(), 6)
        for extra in ("autotune", "compile_cache", "prefetch"):
            if extra in core:
                rec[extra] = core[extra]

        # registry mirrors
        _STEPS.inc()
        if rec["skipped"]:
            _SKIPPED.inc()
        if rec["loss"] is not None:
            _LOSS.set(rec["loss"])
        if rec["grad_norm"] is not None:
            _GNORM.set(rec["grad_norm"])
        if rec["lr"] is not None:
            _LR.set(rec["lr"])
        if "samples_per_s" in rec:
            _SPS.set(rec["samples_per_s"])
        if "tokens_per_s" in rec:
            _TPS.set(rec["tokens_per_s"])
        if "mfu" in rec:
            _MFU.set(rec["mfu"])
        for p in ("data", "compute", "reduce"):
            if phases.get(p):
                _PHASE_S.inc(phases[p], phase=p)
                _PHASE_H.observe(phases[p], phase=p)
                self._totals[p] = self._totals.get(p, 0.0) + phases[p]

        with self._lock:
            self._staged = rec
            self._last = rec
        flight_recorder.get_flight_recorder().record_step(rec)
        if rec["step"] % _MEM_EVERY == 0:
            try:
                from . import memory as _memory

                _memory.update_memory_gauges()
            except Exception:  # noqa: BLE001 — gauges must not break steps
                pass
        return rec

    def last_record(self) -> Optional[Dict[str, Any]]:
        """The most recent staged step record (what the anomaly engine and
        cluster publisher read right after TrainStep returns). Late phase
        merges (save) mutate this dict in place."""
        with self._lock:
            return self._last or None

    def event(self, kind: str, **data) -> None:
        """Irregular event (compile, recompile, preemption...): written to
        the event log immediately and noted in the flight recorder."""
        if not enabled():
            return
        if kind in ("compile", "recompile"):
            _COMPILES.inc(kind=data.get("what", kind))
        rec = {"kind": str(kind), "ts": time.time()}
        rec.update(data)
        sink = self._sink()
        if sink is not None:
            sink.emit(rec)
        flight_recorder.get_flight_recorder().note(kind, **data)

    # -- flushing ----------------------------------------------------------
    def _write(self, rec: Dict[str, Any]) -> None:
        sink = self._sink()
        if sink is not None:
            sink.emit(rec)
        self.records_emitted += 1
        self._flushed += 1
        if self._flushed % _PROM_EVERY == 0:
            try:
                self.export_prometheus()
            except OSError:
                pass

    def finalize(self) -> None:
        """Flush the staged record and rewrite the Prometheus textfile —
        call at end of run (ResilientTrainer does)."""
        with self._lock:
            staged, self._staged = self._staged, None
        if staged is not None:
            self._write(staged)
        try:
            self.export_prometheus()
        except OSError:
            pass

    flush = finalize

    # -- summaries ---------------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        """Aggregate view for bench outputs: mean per-phase ms + last-step
        throughput figures."""
        n = max(self.records_emitted +
                (1 if self._staged is not None else 0), 1)
        out: Dict[str, Any] = {
            "records": self.records_emitted,
            "phase_ms_avg": {p: round(self._totals.get(p, 0.0) / n * 1e3, 3)
                             for p in PHASES},
        }
        last = dict(self._last)
        for k in ("step", "loss", "grad_norm", "samples_per_s",
                  "tokens_per_s", "mfu"):
            if last.get(k) is not None:
                out[f"last_{k}"] = last[k]
        return out


def _f(v) -> Optional[float]:
    if v is None:
        return None
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


_telemetry: Optional[StepTelemetry] = None
_telemetry_lock = threading.Lock()


def get_telemetry() -> StepTelemetry:
    global _telemetry
    with _telemetry_lock:
        if _telemetry is None:
            _telemetry = StepTelemetry()
        return _telemetry


def reset() -> None:
    """Fresh singleton (tests / new runs); closes the open event log."""
    global _telemetry
    with _telemetry_lock:
        if _telemetry is not None and _telemetry._jsonl is not None:
            _telemetry._jsonl.close()
        _telemetry = None
