"""Crash flight recorder: bounded ring of recent step records + span tails,
dumped atomically on failure.

Motivation (ISSUE r9): the NaN step-guard skips a poisoned step and the
PreemptionHandler exits cleanly, but neither leaves forensics — after the
process is gone there is no record of WHAT the last N steps looked like.
The flight recorder is an aircraft-style black box: telemetry keeps pushing
step records into a ring bounded by FLAGS_flight_recorder_steps, and on a
trigger (NaN guard trip, preemption, uncaught trainer exception, or an
explicit `dump()`) the ring + recent spans + a full metrics snapshot are
written with the same tmp+os.replace discipline as CheckpointManager — a
crash mid-dump can never leave a torn file for the post-mortem tooling.

Dumps land in FLAGS_metrics_dir/flight/ (or ./flight_recorder when no
metrics dir is set). The whole module is inert while FLAGS_metrics is off.
"""
from __future__ import annotations

import itertools
import json
import os
import tempfile
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional

from . import spans
from .registry import counter, default_registry, metrics_enabled
from .sinks import _json_default

from ..core.flags import define_flag, get_flag

define_flag(
    "flight_recorder_steps", 64,
    "Ring-buffer capacity of the crash flight recorder: how many of the "
    "most recent per-step telemetry records survive into a crash dump.")

_DUMPS = counter("flight_recorder_dumps_total",
                 "Flight-recorder dumps written, by trigger reason.",
                 labelnames=("reason",), always=True)

_EVENT_RING = 256
_SPAN_TAIL = 200
_ANOMALY_RING = 32

# two triggers inside one second used to collide on the timestamped dump
# filename (the later os.replace silently overwrote the earlier dump);
# a process-wide monotonic sequence makes every dump name unique
_DUMP_SEQ = itertools.count()


def safe_reason(reason: str) -> str:
    """Filesystem-safe dump-name suffix from a trigger reason. The ONE
    sanitizer for every dump path — training triggers and the serving
    flight arm share it, so dumps from both sort and grep uniformly."""
    return "".join(c if c.isalnum() or c in "-_" else "_"
                   for c in str(reason))[:48]


def dump_filename(reason: str, n: int) -> str:
    """Shared dump naming scheme: flight_<wallclock>_<pid>_<instance
    count>_<process seq>_<reason>.json. The per-instance count n resets
    with its recorder; the process-wide _DUMP_SEQ does not — two triggers
    in the same second (or across a recorder reset) can never collide."""
    seq = next(_DUMP_SEQ)
    return (f"flight_{time.strftime('%Y%m%d_%H%M%S')}_{os.getpid()}"
            f"_{int(n):03d}_{seq:04d}_{safe_reason(reason)}.json")

# last cluster view published by observability/cluster.py (rank 0 only);
# module-level so it survives FlightRecorder reset() between run()s
_cluster_snapshot: Optional[Dict[str, Any]] = None
_cluster_lock = threading.Lock()


def set_cluster_snapshot(snapshot: Dict[str, Any]) -> None:
    """Latest cluster aggregation/straggler view, embedded in every dump."""
    global _cluster_snapshot
    with _cluster_lock:
        _cluster_snapshot = snapshot


def cluster_snapshot() -> Optional[Dict[str, Any]]:
    with _cluster_lock:
        return _cluster_snapshot


def note_anomaly(event: Dict[str, Any]) -> None:
    """Record one anomaly event into the recorder's bounded anomaly ring
    (anomaly.AnomalyEngine calls this on every detection, dump or not)."""
    get_flight_recorder().record_anomaly(event)


class FlightRecorder:
    """Bounded in-memory black box; `dump()` serializes it atomically."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = max(int(get_flag("flight_recorder_steps")), 1)
        self.capacity = capacity
        self._lock = threading.Lock()
        self._steps: deque = deque(maxlen=capacity)
        self._events: deque = deque(maxlen=_EVENT_RING)
        self._anomalies: deque = deque(maxlen=_ANOMALY_RING)
        self._dump_count = 0

    # -- feeding -----------------------------------------------------------
    def record_step(self, record: Dict[str, Any]) -> None:
        """Push one per-step telemetry record (dict is kept by REFERENCE so
        late phase merges — e.g. save time added after the step — are still
        visible in a later dump)."""
        with self._lock:
            self._steps.append(record)

    def note(self, kind: str, **data) -> None:
        """Record an irregular event (compile, nan_skip, preemption, ...)."""
        ev = {"kind": str(kind), "ts": time.time()}
        ev.update(data)
        with self._lock:
            self._events.append(ev)

    def record_anomaly(self, event: Dict[str, Any]) -> None:
        """Push one anomaly event into the bounded anomaly ring; the last
        K of these ride along in every subsequent dump."""
        with self._lock:
            self._anomalies.append(dict(event))

    # -- reading -----------------------------------------------------------
    def steps(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._steps)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def anomalies(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._anomalies)

    # -- dumping -----------------------------------------------------------
    def _dump_dir(self, directory: Optional[str]) -> str:
        if directory:
            return os.path.abspath(directory)
        mdir = str(get_flag("metrics_dir") or "")
        if mdir:
            return os.path.join(os.path.abspath(mdir), "flight")
        return os.path.abspath("flight_recorder")

    def dump(self, reason: str, exc: Optional[BaseException] = None,
             directory: Optional[str] = None,
             extra: Optional[Dict[str, Any]] = None) -> str:
        """Write the black box to disk atomically; returns the dump path.
        `extra` keys are merged into the payload (e.g. the anomaly engine
        attaches the triggering anomaly under "anomaly")."""
        with self._lock:
            self._dump_count += 1
            n = self._dump_count
            steps = list(self._steps)
            events = list(self._events)
            anomalies = list(self._anomalies)
        payload: Dict[str, Any] = {
            "kind": "flight_recorder_dump",
            "reason": str(reason),
            "ts": time.time(),
            "time": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "pid": os.getpid(),
            "capacity": self.capacity,
            "steps": steps,
            "events": events,
            "anomalies": anomalies,
            "spans": spans.tail(_SPAN_TAIL),
            "metrics": default_registry().snapshot(),
        }
        cluster = cluster_snapshot()
        if cluster is not None:
            payload["cluster"] = cluster
        if extra:
            for k, v in extra.items():
                payload.setdefault(k, v)
        if exc is not None:
            payload["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc),
                "traceback": "".join(traceback.format_exception(
                    type(exc), exc, exc.__traceback__))[-8000:],
            }
        d = self._dump_dir(directory)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, dump_filename(reason, n))
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(payload, f, default=_json_default)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)  # <- the commit point
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        _DUMPS.inc(reason=safe_reason(reason) or "manual")
        return path


_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def get_flight_recorder() -> FlightRecorder:
    global _recorder
    with _recorder_lock:
        if _recorder is None:
            _recorder = FlightRecorder()
        return _recorder


def reset() -> None:
    """Drop the singleton (tests; also re-reads FLAGS_flight_recorder_steps)."""
    global _recorder, _cluster_snapshot
    with _recorder_lock:
        _recorder = None
    with _cluster_lock:
        _cluster_snapshot = None


# -- runtime trigger hooks (called by jit/, resilience/) --------------------
def on_nan_skip(step: int, loss: Optional[float] = None) -> Optional[str]:
    """NaN step-guard tripped: leave forensics. No-op while metrics are off
    (the guard itself still skips the step either way)."""
    if not metrics_enabled():
        return None
    rec = get_flight_recorder()
    rec.note("nan_skip", step=int(step), loss=loss)
    return rec.dump("nan_guard")


def on_preemption(reason: str) -> Optional[str]:
    """PreemptionHandler latched (SIGTERM / elastic shrink)."""
    if not metrics_enabled():
        return None
    rec = get_flight_recorder()
    rec.note("preemption", reason=str(reason))
    return rec.dump(f"preemption_{reason}")


def on_membership_change(info: Dict[str, Any]) -> Optional[str]:
    """Elastic membership view adopted (rank lost/ejected/joined). The
    dump carries the generation transition so a post-mortem can line the
    loss trajectory up against exactly when the mesh reformed. No-op
    while metrics are off."""
    if not metrics_enabled():
        return None
    rec = get_flight_recorder()
    rec.note("membership_change", **{k: info[k] for k in sorted(info)})
    return rec.dump(f"membership_gen{info.get('gen', '?')}",
                    extra={"membership": dict(info)})


def on_member_ejected(info: Dict[str, Any]) -> Optional[str]:
    """A chronically slow rank was auto-ejected by ElasticTrainer (pinned
    at the rebalance clamp past FLAGS_elastic_eject_patience windows).
    Distinct from membership_change — this is a DECISION, recorded with
    the evidence (streak, weight) that justified it. No-op while metrics
    are off."""
    if not metrics_enabled():
        return None
    rec = get_flight_recorder()
    rec.note("member_ejected", **{k: info[k] for k in sorted(info)})
    return rec.dump(f"eject_member{info.get('member', '?')}",
                    extra={"ejection": dict(info)})


def on_exception(exc: BaseException) -> Optional[str]:
    """Uncaught exception escaping ResilientTrainer.run."""
    if not metrics_enabled():
        return None
    rec = get_flight_recorder()
    rec.note("exception", type=type(exc).__name__, message=str(exc)[:500])
    return rec.dump("exception", exc=exc)
