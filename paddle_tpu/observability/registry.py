"""Process-wide metrics registry: Counter / Gauge / Histogram with labels.

Reference analogs: the reference scatters runtime counters across private
module state (phi autotune cache stats, buffered-reader queue depths, the
profiler's benchmark timer); monitoring systems then re-derive them from
logs. Here every subsystem registers through ONE registry so a live training
run exports a single consistent snapshot — the Prometheus client-library
model (textfile exporter, sinks.py) without the dependency.

Overhead contract (ISSUE r9): recording is a dict lookup + float add under a
per-metric lock — O(100ns). Metrics default to respecting FLAGS_metrics
("off" makes `inc/set/observe` return immediately); subsystems whose legacy
stats must keep counting regardless (autotune._STATS, DevicePrefetcher.stats,
compile-cache counters — their back-compat views read through the registry)
register with `always=True`.
"""
from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..core.flags import define_flag, get_flag

define_flag(
    "metrics", "off",
    "Unified observability layer (observability/): 'on' enables per-step "
    "training telemetry, metric sinks, span recording, and the crash "
    "flight recorder; 'off' reduces the whole layer to near-zero-overhead "
    "no-ops (legacy cache/prefetch counters keep counting).")
define_flag(
    "metrics_dir", "",
    "Directory for metric sinks: events.jsonl (append-only telemetry "
    "event log), paddle_tpu.prom (Prometheus textfile exporter), and "
    "flight/ (crash flight-recorder dumps). Empty = in-memory only.")

_TRUE = ("1", "on", "true", "yes")


def metrics_enabled() -> bool:
    return str(get_flag("metrics")).lower() in _TRUE


# default histogram bounds: latencies in seconds, 100µs .. 100s
_DEFAULT_BUCKETS = (1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3,
                    1.0, 3.0, 10.0, 30.0, 100.0)


class _Metric:
    """Base: one named metric holding per-label-set values."""

    kind = "untyped"

    def __init__(self, name: str, doc: str = "",
                 labelnames: Sequence[str] = (), always: bool = False):
        self.name = name
        self.doc = doc
        self.labelnames = tuple(labelnames)
        self.always = bool(always)
        self._lock = threading.Lock()
        self._values: Dict[Tuple[str, ...], float] = {}

    # -- label plumbing ----------------------------------------------------
    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}")
        return tuple(str(labels[n]) for n in self.labelnames)

    def labels(self, **labels) -> "_Bound":
        return _Bound(self, self._key(labels))

    def _enabled(self) -> bool:
        return self.always or metrics_enabled()

    # -- reading -----------------------------------------------------------
    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def total(self) -> float:
        """Sum across all label sets (0.0 when never recorded)."""
        with self._lock:
            return sum(self._values.values())

    def samples(self) -> List[Tuple[Dict[str, str], float]]:
        with self._lock:
            items = list(self._values.items())
        return [(dict(zip(self.labelnames, k)), v) for k, v in items]

    # -- internal write (also used by back-compat stat views) --------------
    def _set_raw(self, value: float, key: Tuple[str, ...] = ()):
        with self._lock:
            self._values[key] = float(value)

    def _add_raw(self, amount: float, key: Tuple[str, ...] = ()):
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def reset(self):
        with self._lock:
            self._values.clear()


class _Bound:
    """A metric bound to one label set (`metric.labels(x=...)`)."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric: _Metric, key: Tuple[str, ...]):
        self._metric = metric
        self._key = key

    def inc(self, amount: float = 1.0):
        if self._metric._enabled():
            self._metric._add_raw(float(amount), self._key)

    def set(self, value: float):
        if self._metric._enabled():
            self._metric._set_raw(float(value), self._key)

    def observe(self, value: float):
        self._metric.observe(value, **dict(
            zip(self._metric.labelnames, self._key)))

    def value(self) -> float:
        with self._metric._lock:
            return self._metric._values.get(self._key, 0.0)


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels):
        if amount < 0:
            raise ValueError("counters only go up")
        if self._enabled():
            self._add_raw(float(amount), self._key(labels))


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels):
        if self._enabled():
            self._set_raw(float(value), self._key(labels))

    def inc(self, amount: float = 1.0, **labels):
        if self._enabled():
            self._add_raw(float(amount), self._key(labels))

    def dec(self, amount: float = 1.0, **labels):
        self.inc(-amount, **labels)


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics): per label set it
    keeps bucket counts for `le` bounds plus _sum and _count."""

    kind = "histogram"

    def __init__(self, name: str, doc: str = "",
                 labelnames: Sequence[str] = (), always: bool = False,
                 buckets: Iterable[float] = _DEFAULT_BUCKETS):
        super().__init__(name, doc, labelnames, always)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        # per label-key: [bucket_counts..., +Inf_count, sum]
        self._hist: Dict[Tuple[str, ...], List[float]] = {}

    def observe(self, value: float, **labels):
        if not self._enabled():
            return
        key = self._key(labels)
        v = float(value)
        with self._lock:
            row = self._hist.get(key)
            if row is None:
                row = self._hist[key] = [0.0] * (len(self.buckets) + 2)
            for i, b in enumerate(self.buckets):
                if v <= b:
                    row[i] += 1
            row[-2] += 1  # +Inf / _count
            row[-1] += v  # _sum
            self._values[key] = row[-2]  # expose count via value()

    def stats(self, **labels) -> Dict[str, float]:
        key = self._key(labels)
        with self._lock:
            row = self._hist.get(key)
            if row is None:
                return {"count": 0, "sum": 0.0}
            return {"count": row[-2], "sum": row[-1]}

    def quantile(self, q: float, **labels) -> Optional[float]:
        """Approximate quantile from the cumulative buckets (linear
        interpolation inside the bucket, Prometheus histogram_quantile
        semantics). Degenerate rows are well-defined rather than
        interpolation artifacts: nan when nothing was observed, the sole
        observation (recovered exactly from _sum) when count == 1 — a
        freshly started replica's rollup must not fabricate a latency.
        q clamps to [0, 1]; observations past the last finite bound
        report that bound."""
        q = min(max(float(q), 0.0), 1.0)
        key = self._key(labels)
        with self._lock:
            row = self._hist.get(key)
            row = list(row) if row is not None else None
        return self._row_quantile(row, q)

    def _row_quantile(self, row: Optional[List[float]],
                      q: float) -> Optional[float]:
        if row is None or row[-2] <= 0:
            return float("nan")
        if row[-2] == 1:
            return row[-1]          # _sum of a single observation IS it
        rank = q * row[-2]
        lo = 0.0
        prev_count = 0.0
        for i, b in enumerate(self.buckets):
            if row[i] >= rank:
                width = b - lo
                in_bucket = row[i] - prev_count
                if in_bucket <= 0:
                    return b
                return lo + width * (rank - prev_count) / in_bucket
            lo, prev_count = b, row[i]
        return self.buckets[-1] if self.buckets else None

    def rollup_quantiles(self, qs=(0.5, 0.95, 0.99)) -> Dict[str, float]:
        """Fleet-level rollup: quantiles over the MERGE of every label
        row (bucket counts and sums are additive), keyed "p50"/"p95"/...
        Empty dict when nothing was observed under any label set."""
        with self._lock:
            rows = [list(r) for r in self._hist.values()]
        merged = None
        for r in rows:
            if r[-2] <= 0:
                continue
            if merged is None:
                merged = list(r)
            else:
                merged = [a + b for a, b in zip(merged, r)]
        if merged is None:
            return {}
        return {f"p{int(round(float(q) * 100))}":
                self._row_quantile(merged, float(q)) for q in qs}

    def samples(self):  # prometheus expansion handled by the text writer
        with self._lock:
            items = list(self._hist.items())
        out = []
        for key, row in items:
            base = dict(zip(self.labelnames, key))
            for i, b in enumerate(self.buckets):
                out.append((dict(base, le=repr(b)), row[i],
                            self.name + "_bucket"))
            out.append((dict(base, le="+Inf"), row[-2], self.name + "_bucket"))
            out.append((base, row[-1], self.name + "_sum"))
            out.append((dict(base), row[-2], self.name + "_count"))
        return out

    def reset(self):
        with self._lock:
            self._values.clear()
            self._hist.clear()


class MetricsRegistry:
    """Name -> metric table. Registration is idempotent: re-registering the
    same (name, kind) returns the existing metric, so subsystems can declare
    their metrics at import time in any order."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, doc, labelnames, always, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}")
                return m
            m = cls(name, doc, labelnames, always, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, doc: str = "",
                labelnames: Sequence[str] = (),
                always: bool = False) -> Counter:
        return self._get_or_create(Counter, name, doc, labelnames, always)

    def gauge(self, name: str, doc: str = "", labelnames: Sequence[str] = (),
              always: bool = False) -> Gauge:
        return self._get_or_create(Gauge, name, doc, labelnames, always)

    def histogram(self, name: str, doc: str = "",
                  labelnames: Sequence[str] = (), always: bool = False,
                  buckets: Iterable[float] = _DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, doc, labelnames, always,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Plain-dict view {metric: {"label=a|label2=b": value}} — what the
        flight recorder embeds in crash dumps."""
        out: Dict[str, Dict[str, float]] = {}
        for m in self.metrics():
            if isinstance(m, Histogram):
                vals = {}
                with m._lock:
                    for key, row in m._hist.items():
                        lbl = "|".join(f"{n}={v}" for n, v in
                                       zip(m.labelnames, key))
                        vals[lbl or "_"] = {"count": row[-2], "sum": row[-1]}
                out[m.name] = vals
                continue
            out[m.name] = {
                "|".join(f"{n}={v}" for n, v in lbls.items()) or "_": val
                for lbls, val in m.samples()}
        return out

    def reset(self):
        """Zero every metric (tests / fresh runs); registrations survive."""
        for m in self.metrics():
            m.reset()


REGISTRY = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return REGISTRY


def counter(name: str, doc: str = "", labelnames: Sequence[str] = (),
            always: bool = False) -> Counter:
    return REGISTRY.counter(name, doc, labelnames, always)


def gauge(name: str, doc: str = "", labelnames: Sequence[str] = (),
          always: bool = False) -> Gauge:
    return REGISTRY.gauge(name, doc, labelnames, always)


def histogram(name: str, doc: str = "", labelnames: Sequence[str] = (),
              always: bool = False,
              buckets: Iterable[float] = _DEFAULT_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, doc, labelnames, always, buckets=buckets)
