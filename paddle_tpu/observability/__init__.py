"""paddle_tpu.observability — unified observability layer (ISSUE r9).

One registry, four capabilities:

  * metrics registry (registry.py): Counter/Gauge/Histogram with labels,
    thread-safe, near-zero overhead while FLAGS_metrics is off;
  * sinks (sinks.py): append-only JSONL event log + atomic Prometheus
    textfile exporter under FLAGS_metrics_dir;
  * per-step telemetry (telemetry.py): the runtime emits loss / grad-norm /
    lr / throughput / MFU / per-phase times from inside jit.TrainStep and
    resilience.ResilientTrainer;
  * span tracing (spans.py) + crash flight recorder (flight_recorder.py):
    one span ring shared by the profiler, the chrome-trace merge, and the
    atomic crash dumps triggered by the NaN guard / preemption / uncaught
    exceptions.

Importing this package registers FLAGS_metrics, FLAGS_metrics_dir, and
FLAGS_flight_recorder_steps.
"""
from . import flight_recorder, registry, sinks, spans, telemetry  # noqa: F401
from .flight_recorder import FlightRecorder, get_flight_recorder  # noqa: F401
from .registry import (REGISTRY, Counter, Gauge, Histogram,  # noqa: F401
                       MetricsRegistry, counter, default_registry, gauge,
                       histogram, metrics_enabled)
from .sinks import (JsonlEventLog, parse_prometheus_text,  # noqa: F401
                    prometheus_text, write_prometheus_textfile)
from .spans import record_span, span  # noqa: F401
from .telemetry import StepTelemetry, get_telemetry  # noqa: F401

__all__ = [
    "REGISTRY", "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "counter", "gauge", "histogram", "default_registry", "metrics_enabled",
    "JsonlEventLog", "prometheus_text", "write_prometheus_textfile",
    "parse_prometheus_text", "span", "record_span", "StepTelemetry",
    "get_telemetry", "FlightRecorder", "get_flight_recorder", "reset_all",
]


def reset_all() -> None:
    """Zero metrics, clear spans, and drop telemetry/flight singletons —
    test isolation helper."""
    registry.REGISTRY.reset()
    spans.clear()
    telemetry.reset()
    flight_recorder.reset()
