"""paddle_tpu.observability — unified observability layer (ISSUE r9 + r10).

One registry, seven capabilities:

  * metrics registry (registry.py): Counter/Gauge/Histogram with labels,
    thread-safe, near-zero overhead while FLAGS_metrics is off;
  * sinks (sinks.py): append-only JSONL event log + atomic Prometheus
    textfile exporter under FLAGS_metrics_dir;
  * per-step telemetry (telemetry.py): the runtime emits loss / grad-norm /
    lr / throughput / MFU / per-phase times from inside jit.TrainStep and
    resilience.ResilientTrainer;
  * span tracing (spans.py) + crash flight recorder (flight_recorder.py):
    one span ring shared by the profiler, the chrome-trace merge, and the
    atomic crash dumps triggered by the NaN guard / preemption / uncaught
    exceptions / anomalies;
  * cluster aggregation (cluster.py): each rank publishes its step record
    through the process-group store; rank 0 aggregates min/median/max/p95
    per phase and flags stragglers (FLAGS_straggler_k / FLAGS_straggler_m);
  * anomaly engine (anomaly.py): rolling-window detectors (loss/grad-norm
    spike, step-time regression, throughput collapse, compile-cache
    collapse) that dump the flight recorder on detection (FLAGS_anomaly);
  * memory accounting (memory.py) + HTTP endpoint (serve.py): per-device
    HBM gauges, per-executable XLA cost/memory analysis, and /metrics +
    /healthz on FLAGS_metrics_port.

Importing this package registers FLAGS_metrics, FLAGS_metrics_dir,
FLAGS_flight_recorder_steps, FLAGS_anomaly, FLAGS_metrics_port,
FLAGS_straggler_k, and FLAGS_straggler_m.
"""
from . import (anomaly, cluster, flight_recorder, memory,  # noqa: F401
               registry, serve, sinks, spans, telemetry)
from .anomaly import AnomalyEngine, anomaly_enabled  # noqa: F401
from .cluster import ClusterTelemetry  # noqa: F401
from .flight_recorder import FlightRecorder, get_flight_recorder  # noqa: F401
from .memory import (device_memory_stats, memory_report,  # noqa: F401
                     note_executable, update_memory_gauges)
from .registry import (REGISTRY, Counter, Gauge, Histogram,  # noqa: F401
                       MetricsRegistry, counter, default_registry, gauge,
                       histogram, metrics_enabled)
from .serve import MetricsServer, start_metrics_server  # noqa: F401
from .sinks import (JsonlEventLog, parse_prometheus_text,  # noqa: F401
                    prometheus_text, write_prometheus_textfile)
from .spans import record_span, span  # noqa: F401
from .telemetry import StepTelemetry, get_telemetry  # noqa: F401

__all__ = [
    "REGISTRY", "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "counter", "gauge", "histogram", "default_registry", "metrics_enabled",
    "JsonlEventLog", "prometheus_text", "write_prometheus_textfile",
    "parse_prometheus_text", "span", "record_span", "StepTelemetry",
    "get_telemetry", "FlightRecorder", "get_flight_recorder", "reset_all",
    "ClusterTelemetry", "AnomalyEngine", "anomaly_enabled", "MetricsServer",
    "start_metrics_server", "device_memory_stats", "update_memory_gauges",
    "note_executable", "memory_report",
]


def reset_all() -> None:
    """Zero metrics, clear spans, stop the HTTP server, and drop the
    telemetry/flight singletons — test isolation helper."""
    registry.REGISTRY.reset()
    spans.clear()
    telemetry.reset()
    flight_recorder.reset()
    serve.reset()
