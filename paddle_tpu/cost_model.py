"""Cost model (reference: python/paddle/cost_model/cost_model.py — profile a
program and report per-op/total costs for the auto-parallel planner).

TPU-native design: XLA already carries an analytical cost model — a lowered
executable exposes cost_analysis() (flops, bytes accessed, estimated
seconds). CostModel wraps it: static costs come from the compiler (no
execution), measured costs from timed runs of the compiled program. This is
the cost source a mesh/parallelism planner should consume, instead of the
reference's profiler-replay machinery.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict

import jax

from .core.tensor import Tensor


def _unwrap(args):
    return tuple(a._value if isinstance(a, Tensor) else a for a in args)


class CostModel:
    def _compile(self, fn, args, kwargs):
        vals = _unwrap(args)

        def pure(*vs):
            out = fn(*(Tensor(v) for v in vs), **kwargs)
            return jax.tree_util.tree_map(
                lambda t: t._value if isinstance(t, Tensor) else t, out,
                is_leaf=lambda t: isinstance(t, Tensor))

        return jax.jit(pure).lower(*vals).compile(), vals

    def static_cost(self, fn: Callable, *args, **kwargs) -> Dict[str, Any]:
        """Compile-time cost analysis — no execution. fn is a Tensor/array
        function; returns {'flops', 'bytes_accessed', 'optimal_seconds', ...}
        from XLA's analytical model."""
        compiled, _ = self._compile(fn, args, kwargs)
        return self._analyze(compiled)

    def _analyze(self, compiled) -> Dict[str, Any]:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, list):  # per-device list on some backends
            analysis = analysis[0] if analysis else {}
        out = {
            "flops": float(analysis.get("flops", 0.0)),
            "bytes_accessed": float(analysis.get("bytes accessed", 0.0)),
            "optimal_seconds": float(analysis.get("optimal_seconds", 0.0)),
        }
        out["raw"] = dict(analysis)
        try:
            mem = compiled.memory_analysis()
            out["peak_bytes"] = int(
                getattr(mem, "temp_size_in_bytes", 0)
                + getattr(mem, "argument_size_in_bytes", 0)
                + getattr(mem, "output_size_in_bytes", 0))
        except Exception:  # pragma: no cover — backend-dependent
            out["peak_bytes"] = 0
        return out

    def profile_measure(self, fn: Callable, *args, repeats: int = 5,
                        **kwargs) -> Dict[str, Any]:
        """Static costs + measured wall time — ONE compilation, reused for
        both the analysis and the timed runs."""
        compiled, vals = self._compile(fn, args, kwargs)
        out = self._analyze(compiled)
        jax.block_until_ready(compiled(*vals))  # warm
        t0 = time.perf_counter()
        for _ in range(repeats):
            res = compiled(*vals)
        jax.block_until_ready(res)
        dt = (time.perf_counter() - t0) / repeats
        out["measured_seconds"] = dt
        if dt > 0 and out["flops"]:
            out["achieved_flops_per_sec"] = out["flops"] / dt
        return out
