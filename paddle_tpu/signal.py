"""paddle.signal analog (reference: python/paddle/signal.py — frame/
overlap_add/stft/istft over phi kernels).

Framing is a strided gather; stft = frame -> window -> rfft, all of which XLA
fuses into batched FFT calls on TPU.
"""
from __future__ import annotations

import jax.numpy as jnp

from .core.tensor import Tensor


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Split into overlapping frames (reference: signal.py frame)."""
    xv = _val(x)
    if axis not in (-1, xv.ndim - 1):
        raise NotImplementedError("frame supports axis=-1")
    n = xv.shape[-1]
    if n < frame_length:
        raise ValueError(
            f"input length {n} is shorter than frame_length {frame_length}")
    num_frames = 1 + (n - frame_length) // hop_length
    idx = (jnp.arange(frame_length)[None, :]
           + hop_length * jnp.arange(num_frames)[:, None])  # [F, L]
    out = jnp.take(xv, idx, axis=-1)  # [..., F, L]
    # reference layout: [..., frame_length, num_frames]
    return Tensor(jnp.swapaxes(out, -1, -2))


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame (reference: signal.py overlap_add).

    x: [..., frame_length, num_frames] -> [..., output_length]
    """
    xv = _val(x)
    if axis not in (-1, xv.ndim - 1):
        raise NotImplementedError("overlap_add supports axis=-1")
    frame_length, num_frames = xv.shape[-2], xv.shape[-1]
    out_len = frame_length + hop_length * (num_frames - 1)
    batch_shape = xv.shape[:-2]
    flat = xv.reshape((-1, frame_length, num_frames))
    out = jnp.zeros((flat.shape[0], out_len), xv.dtype)
    idx = (jnp.arange(frame_length)[:, None]
           + hop_length * jnp.arange(num_frames)[None, :])  # [L, F]
    out = out.at[:, idx.reshape(-1)].add(flat.reshape(flat.shape[0], -1))
    return Tensor(out.reshape(batch_shape + (out_len,)))


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """Short-time Fourier transform (reference: signal.py stft).

    Returns [..., n_fft//2+1 (or n_fft), num_frames] complex.
    """
    xv = _val(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        win = jnp.ones((win_length,), jnp.float32)
    else:
        win = _val(window).astype(jnp.float32)
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        win = jnp.pad(win, (lpad, n_fft - win_length - lpad))
    if center:
        pad = [(0, 0)] * (xv.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        xv = jnp.pad(xv, pad, mode=pad_mode)
    frames = frame(Tensor(xv), n_fft, hop_length)._value  # [..., n_fft, F]
    frames = frames * win[:, None]
    if onesided:
        spec = jnp.fft.rfft(frames, axis=-2)
    else:
        spec = jnp.fft.fft(frames, axis=-2)
    if normalized:
        spec = spec / jnp.sqrt(jnp.float32(n_fft))
    return Tensor(spec)


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    """Inverse STFT with window-envelope normalization (reference: signal.py
    istft)."""
    sv = _val(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        win = jnp.ones((win_length,), jnp.float32)
    else:
        win = _val(window).astype(jnp.float32)
    if win_length < n_fft:
        lpad = (n_fft - win_length) // 2
        win = jnp.pad(win, (lpad, n_fft - win_length - lpad))
    if normalized:
        sv = sv * jnp.sqrt(jnp.float32(n_fft))
    if onesided:
        frames = jnp.fft.irfft(sv, n=n_fft, axis=-2)  # [..., n_fft, F]
    elif return_complex:
        frames = jnp.fft.ifft(sv, axis=-2)  # complex reconstruction
    else:
        frames = jnp.fft.ifft(sv, axis=-2).real
    frames = frames * win[:, None]
    y = overlap_add(Tensor(frames), hop_length)._value
    # normalize by the summed squared-window envelope
    wsq = jnp.tile(win[:, None] ** 2, (1, sv.shape[-1]))
    envelope = overlap_add(Tensor(wsq), hop_length)._value
    y = y / jnp.maximum(envelope, 1e-10)
    if center:
        y = y[..., n_fft // 2: y.shape[-1] - n_fft // 2]
    if length is not None:
        y = y[..., :length]
    return Tensor(y)


__all__ = ["frame", "overlap_add", "stft", "istft"]
