"""paddle.autograd analog.

Reference: python/paddle/autograd/ — py_layer.py (PyLayer/PyLayerContext),
saved_tensors_hooks.py, backward(), plus the functional jvp/vjp/Jacobian/
Hessian API from python/paddle/incubate/autograd/functional.py.

TPU-native: PyLayer plugs a user-defined backward into the same GradNode graph
the op registry builds (core/autograd.py), so custom autograd composes with
generated vjps; the functional API lowers to jax.jvp/jacrev/hessian over a
functionalized view of the user callable, which is exactly the reference's
"double-backward via graph re-tracing" collapsed into compiler transforms.
"""
from __future__ import annotations

import threading
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..core.autograd import (  # noqa: F401
    GradNode,
    enable_grad,
    grad,
    is_grad_enabled,
    no_grad,
    run_backward,
    set_grad_enabled,
)
from ..core.tensor import Tensor


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward (reference: python/paddle/autograd/autograd.py)."""
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is not None and not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    run_backward(tensors, grad_tensors, retain_graph=retain_graph)


# --------------------------------------------------------------------------
# PyLayer (reference: python/paddle/autograd/py_layer.py + fluid/eager/pylayer/)
# --------------------------------------------------------------------------

_hooks_state = threading.local()


class PyLayerContext:
    """Context handed to forward/backward (reference: py_layer.py:35)."""

    def __init__(self):
        self._saved = ()
        self._unpack = None
        self.materialize_grads = True
        self._non_differentiable = set()

    def save_for_backward(self, *tensors):
        pack = getattr(_hooks_state, "pack", None)
        if pack is not None:
            self._saved = tuple(pack(t) if isinstance(t, Tensor) else t for t in tensors)
            self._unpack = getattr(_hooks_state, "unpack", None)
        else:
            self._saved = tensors
            self._unpack = None

    def saved_tensor(self):
        if self._unpack is not None:
            out = tuple(self._unpack(t) for t in self._saved)
        else:
            out = self._saved
        return list(out)

    def mark_non_differentiable(self, *tensors):
        self._non_differentiable.update(id(t) for t in tensors)

    def set_materialize_grads(self, value: bool):
        self.materialize_grads = bool(value)


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """User-defined autograd op (reference: py_layer.py:93 class PyLayer).

    Subclass with @staticmethod forward(ctx, *args) and backward(ctx, *grads);
    call via MyLayer.apply(...). The backward is recorded as a GradNode so it
    interoperates with every registry op's vjp.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()

        tensor_inputs: List[Tensor] = []
        for a in args:
            if isinstance(a, Tensor):
                tensor_inputs.append(a)
        for v in kwargs.values():
            if isinstance(v, Tensor):
                tensor_inputs.append(v)

        grad_needed = is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs
        )

        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)

        single = not isinstance(out, (tuple, list))
        out_list = [out] if single else list(out)

        if not grad_needed:
            return out

        edges = []
        for t in tensor_inputs:
            if t.stop_gradient:
                edges.append(None)
            elif t._grad_node is not None:
                node, idx = t._grad_node
                edges.append(("node", node, idx))
            else:
                edges.append(("leaf", t))

        out_avals = [jax.ShapeDtypeStruct(tuple(o.shape), o.dtype) for o in out_list]
        n_outputs = len(out_list)

        def vjp_fn(cotangents):
            cots = (cotangents,) if n_outputs == 1 else tuple(cotangents)
            grad_ts = []
            for c, aval in zip(cots, out_avals):
                gt = Tensor(c if not hasattr(c, "dtype") or c.dtype != jax.dtypes.float0 else jnp.zeros(aval.shape, aval.dtype))
                gt.stop_gradient = True
                grad_ts.append(gt)
            with no_grad():
                in_grads = cls.backward(ctx, *grad_ts)
            if not isinstance(in_grads, (tuple, list)):
                in_grads = (in_grads,)
            vals = []
            for g in in_grads:
                if g is None:
                    vals.append(None)
                else:
                    vals.append(g._value if isinstance(g, Tensor) else jnp.asarray(g))
            # pad in case backward returned fewer grads than tensor inputs
            while len(vals) < len(edges):
                vals.append(None)
            return tuple(vals)

        node = GradNode(f"PyLayer[{cls.__name__}]", vjp_fn, edges, out_avals)

        wrapped = []
        for i, o in enumerate(out_list):
            if id(o) in ctx._non_differentiable or not jnp.issubdtype(o.dtype, jnp.inexact):
                wrapped.append(o)
                continue
            t = Tensor(o._value)
            t.stop_gradient = False
            t._grad_node = (node, i)
            wrapped.append(t)
        return wrapped[0] if single else tuple(wrapped)


LegacyPyLayer = PyLayer  # reference keeps an alias for the pre-eager API


class saved_tensors_hooks:
    """Reference: python/paddle/autograd/saved_tensors_hooks.py.

    Registers pack/unpack hooks applied to tensors stashed via
    PyLayerContext.save_for_backward. (Registry-op residuals live inside XLA
    programs and are managed by the compiler, so — unlike the CUDA reference —
    there is no host-visible stash to intercept for built-in ops.)
    """

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        self._prev = (
            getattr(_hooks_state, "pack", None),
            getattr(_hooks_state, "unpack", None),
        )
        _hooks_state.pack = self.pack_hook
        _hooks_state.unpack = self.unpack_hook
        return self

    def __exit__(self, *exc):
        _hooks_state.pack, _hooks_state.unpack = self._prev
        return False


# --------------------------------------------------------------------------
# Functional transforms (reference: incubate/autograd/functional.py)
# --------------------------------------------------------------------------


def _functionalize(func: Callable):
    """Lift a Tensor->Tensor callable to a jax value->value function."""

    def fn(*vals):
        ts = [Tensor(v) for v in vals]
        with no_grad():
            out = func(*ts)
        if isinstance(out, (tuple, list)):
            return tuple(o._value for o in out)
        return out._value

    return fn


def _tensorize(vals):
    if isinstance(vals, (tuple, list)):
        return tuple(Tensor(v) for v in vals)
    return Tensor(vals)


def _values(xs):
    if isinstance(xs, (tuple, list)):
        return [x._value if isinstance(x, Tensor) else jnp.asarray(x) for x in xs]
    return [xs._value if isinstance(xs, Tensor) else jnp.asarray(xs)]


def vjp(func, xs, v=None):
    """paddle.incubate.autograd.vjp(func, xs, v) -> (out, vjp_result)."""
    vals = _values(xs)
    fn = _functionalize(func)
    out, pullback = jax.vjp(fn, *vals)
    if v is None:
        cot = jnp.ones_like(out) if not isinstance(out, tuple) else tuple(jnp.ones_like(o) for o in out)
    else:
        cot_vals = _values(v)
        cot = cot_vals[0] if not isinstance(out, tuple) else tuple(cot_vals)
    grads = pullback(cot)
    grads_t = tuple(Tensor(g) for g in grads)
    out_t = _tensorize(out)
    return out_t, grads_t if isinstance(xs, (tuple, list)) else grads_t[0]


def jvp(func, xs, v=None):
    """paddle.incubate.autograd.jvp(func, xs, v) -> (out, jvp_result)."""
    vals = _values(xs)
    fn = _functionalize(func)
    if v is None:
        tangents = tuple(jnp.ones_like(x) for x in vals)
    else:
        tangents = tuple(_values(v))
    out, jv = jax.jvp(fn, tuple(vals), tangents)
    return _tensorize(out), _tensorize(jv)


class Jacobian:
    """Lazy Jacobian (reference: incubate/autograd/functional.py:Jacobian).

    Index with [i, j] blocks or materialize via .numpy()/tensor conversion.
    """

    def __init__(self, func, xs, is_batched=False):
        self._vals = _values(xs)
        self._multi = isinstance(xs, (tuple, list))
        fn = _functionalize(func)
        jac = jax.jacrev(fn, argnums=tuple(range(len(self._vals))))(*self._vals)
        # jac: per-output tree of per-input jacobians; normalize to Tensor(s)
        if isinstance(jac, tuple) and self._multi:
            self._jac = tuple(Tensor(j) for j in jac)
        else:
            self._jac = Tensor(jac[0] if isinstance(jac, tuple) and len(jac) == 1 else jac)

    def __getitem__(self, idx):
        if isinstance(self._jac, tuple):
            return self._jac[idx]
        return Tensor(self._jac._value[idx])

    @property
    def shape(self):
        if isinstance(self._jac, tuple):
            return [j.shape for j in self._jac]
        return self._jac.shape

    def numpy(self):
        if isinstance(self._jac, tuple):
            return tuple(j.numpy() for j in self._jac)
        return self._jac.numpy()

    def tensor(self):
        return self._jac


class Hessian:
    """Lazy Hessian of a scalar-valued function."""

    def __init__(self, func, xs, is_batched=False):
        self._vals = _values(xs)
        fn = _functionalize(func)
        hes = jax.hessian(fn, argnums=tuple(range(len(self._vals))))(*self._vals)
        if len(self._vals) == 1:
            self._hes = Tensor(hes[0][0] if isinstance(hes, tuple) else hes)
        else:
            self._hes = tuple(tuple(Tensor(b) for b in row) for row in hes)

    def __getitem__(self, idx):
        if isinstance(self._hes, tuple):
            return self._hes[idx]
        return Tensor(self._hes._value[idx])

    @property
    def shape(self):
        if isinstance(self._hes, tuple):
            return [[b.shape for b in row] for row in self._hes]
        return self._hes.shape

    def numpy(self):
        if isinstance(self._hes, tuple):
            return tuple(tuple(b.numpy() for b in row) for row in self._hes)
        return self._hes.numpy()

    def tensor(self):
        return self._hes


def jacobian(func, xs, create_graph=False, allow_unused=False):
    """Dense Jacobian convenience wrapper returning Tensor(s)."""
    return Jacobian(func, xs).tensor()


def hessian(func, xs, create_graph=False, allow_unused=False):
    """Dense Hessian convenience wrapper returning Tensor(s)."""
    return Hessian(func, xs).tensor()


__all__ = [
    "backward",
    "grad",
    "no_grad",
    "enable_grad",
    "set_grad_enabled",
    "is_grad_enabled",
    "PyLayer",
    "PyLayerContext",
    "LegacyPyLayer",
    "saved_tensors_hooks",
    "vjp",
    "jvp",
    "Jacobian",
    "Hessian",
    "jacobian",
    "hessian",
]
