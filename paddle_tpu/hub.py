"""paddle.hub (reference python/paddle/hapi/hub.py): hubconf.py model
loading. This environment has no egress, so the 'github' source is
unavailable by policy; 'local' directories and importable modules work
fully — load/list/help against any repo_dir with a hubconf.py."""
from __future__ import annotations

import importlib
import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUB_CONF = "hubconf.py"


_loaded = {}


def _import_hubconf(repo_dir: str, source: str, force_reload: bool = False):
    if source == "github":
        raise RuntimeError(
            "paddle.hub github source needs network egress, which this "
            "environment forbids; clone the repo and use source='local'")
    if os.path.isdir(repo_dir):
        path = os.path.join(repo_dir, _HUB_CONF)
        if not os.path.exists(path):
            raise FileNotFoundError(f"no {_HUB_CONF} in {repo_dir}")
        key = os.path.abspath(path)
        if not force_reload and key in _loaded:
            return _loaded[key]
        # one module slot PER repo: a second repo's hubconf must not
        # shadow the first's
        mod_name = f"hubconf_{abs(hash(key)) & 0xffffffff:x}"
        spec = importlib.util.spec_from_file_location(mod_name, path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[mod_name] = mod
        spec.loader.exec_module(mod)
        _loaded[key] = mod
        return mod
    mod = importlib.import_module(repo_dir)
    if force_reload:
        mod = importlib.reload(mod)
    return mod


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    """Entrypoint names exported by the repo's hubconf (callables not
    starting with '_')."""
    mod = _import_hubconf(repo_dir, source, force_reload)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    mod = _import_hubconf(repo_dir, source, force_reload)
    return getattr(mod, model).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    mod = _import_hubconf(repo_dir, source, force_reload)
    entry = getattr(mod, model, None)
    if entry is None or not callable(entry):
        raise RuntimeError(f"no callable entrypoint {model!r} in {repo_dir}")
    return entry(**kwargs)
