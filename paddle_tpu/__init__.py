"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's
capability surface, built on JAX/XLA/Pallas.

Layering (mirrors SURVEY.md §1 of the reference analysis):
  core/     tensor + autograd + device/flags         (L0, L3a)
  ops/      YAML op registry + jax kernels           (L1, L2)
  nn/ ...   user API                                  (L4)
  jit/      trace-and-compile executor                (L3b/L3c -> XLA)
  distributed/  mesh, collectives, parallelism        (L5)
"""
from __future__ import annotations

from .core import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    Place,
    TPUPlace,
    Tensor,
    device_count,
    enable_grad,
    get_device,
    grad,
    is_grad_enabled,
    no_grad,
    set_device,
    set_grad_enabled,
    to_tensor,
)
from .core.dtype import (  # noqa: F401
    bfloat16,
    bool_ as bool,  # noqa: A001
    complex64,
    complex128,
    float16,
    float32,
    float64,
    get_default_dtype,
    int8,
    int16,
    int32,
    int64,
    set_default_dtype,
    uint8,
)
from .core.flags import get_flags, set_flags  # noqa: F401
from .core.random import get_rng_state, seed, set_rng_state  # noqa: F401

from . import ops  # noqa: F401  (loads the YAML registry)
from . import tensor_methods  # noqa: F401  (installs Tensor methods)

# Re-export every registered op as a top-level function (paddle.add, ...).
# Names matching submodules (paddle.fft the namespace vs the fft op) stay
# module-valued at top level, as in the reference.
import sys as _sys

_SUBMODULE_NAMES = {"fft", "signal", "audio", "text", "sparse", "linalg"}
_this = _sys.modules[__name__]
for _name in ops.all_ops():
    if _name not in _SUBMODULE_NAMES and not hasattr(_this, _name):
        setattr(_this, _name, getattr(ops.api, _name))
del _name, _this, _sys

# paddle-style aliases
mod = ops.api.remainder
multiply_add = ops.api.multiply_add
concat = ops.api.concat


def add_n(inputs):
    """paddle.add_n: elementwise sum of a list of tensors."""
    out = inputs[0]
    for x in inputs[1:]:
        out = ops.api.add(out, x)
    return out


from . import amp  # noqa: F401, E402
from . import device  # noqa: F401, E402
from . import nn  # noqa: F401, E402
from . import optimizer  # noqa: F401, E402
from . import io  # noqa: F401, E402
from . import jit  # noqa: F401, E402
from . import metric  # noqa: F401, E402
from . import vision  # noqa: F401, E402
from . import distributed  # noqa: F401, E402
from . import static  # noqa: F401, E402
from . import models  # noqa: F401, E402
from . import distribution  # noqa: F401, E402
from . import autograd  # noqa: F401, E402
from . import sparse  # noqa: F401, E402
from . import profiler  # noqa: F401, E402
from . import geometric  # noqa: F401, E402
from . import quantization  # noqa: F401, E402
from . import fft  # noqa: F401, E402
from . import callbacks  # noqa: F401, E402
from . import hub  # noqa: F401, E402
from . import linalg  # noqa: F401, E402
from . import regularizer  # noqa: F401, E402
from . import sysconfig  # noqa: F401, E402
from . import signal  # noqa: F401, E402
from . import audio  # noqa: F401, E402
from . import text  # noqa: F401, E402
from . import inference  # noqa: F401, E402
from . import onnx  # noqa: F401, E402
from . import incubate  # noqa: F401, E402
from . import utils  # noqa: F401, E402
from . import multiprocessing  # noqa: F401, E402
from . import cost_model  # noqa: F401, E402
from . import crypto  # noqa: F401, E402
from . import resilience  # noqa: F401, E402
from .framework.io import load, save  # noqa: F401, E402
from .framework.containers import (  # noqa: F401, E402
    SelectedRows, TensorArray, array_length, array_read, array_write,
    create_array,
)
from .hapi.model import Model, summary  # noqa: F401, E402
from .api_extra import *  # noqa: F401, F403, E402 (reference __all__ parity)
tensor_methods._install_extra_methods()

# top-level inplace twins (paddle.tanh_(x) etc. — reference exposes the
# method AND a function for each inplace op)
import sys as _sys


def _install_inplace_functions():
    this = _sys.modules[__name__]
    for _n in dir(Tensor):
        if _n.endswith("_") and not _n.startswith("_") \
                and not hasattr(this, _n):
            def _mk(meth):
                def fn(x, *a, **k):
                    return getattr(x, meth)(*a, **k)

                fn.__name__ = meth
                fn.__doc__ = f"In-place variant: Tensor.{meth}."
                return fn

            setattr(this, _n, _mk(_n))


_install_inplace_functions()
del _sys

version = "0.1.0"
__version__ = version


def disable_static():
    from .static import disable_static as _ds

    _ds()


def enable_static():
    from .static import _enable_static_mode

    _enable_static_mode()


def in_dynamic_mode():
    from .static import _in_static_mode

    return not _in_static_mode()


def is_grad_enabled_():  # legacy alias
    return is_grad_enabled()
