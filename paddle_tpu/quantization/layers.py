"""Quantized layer wrappers (reference: paddle/nn/quant/ QuantedLinear etc.)."""
from __future__ import annotations

from ..nn import functional as NF
from ..nn.layer import Layer
from .quanters import FakeQuanterWithAbsMax


class QuantedLinear(Layer):
    """Linear with fake-quant on activation + weight."""

    def __init__(self, source, activation_quanter=None, weight_quanter=None):
        super().__init__()
        self.weight = source.weight
        self.bias = getattr(source, "bias", None)
        self.activation_quanter = activation_quanter or FakeQuanterWithAbsMax()
        self.weight_quanter = weight_quanter or FakeQuanterWithAbsMax()

    def forward(self, x):
        x = self.activation_quanter(x)
        w = self.weight_quanter(self.weight)
        return NF.linear(x, w, self.bias)


class QuantedConv2D(Layer):
    def __init__(self, source, activation_quanter=None, weight_quanter=None):
        super().__init__()
        self._source = source
        self.weight = source.weight
        self.bias = getattr(source, "bias", None)
        self.activation_quanter = activation_quanter or FakeQuanterWithAbsMax()
        self.weight_quanter = weight_quanter or FakeQuanterWithAbsMax()

    def forward(self, x):
        from ..ops import api

        x = self.activation_quanter(x)
        w = self.weight_quanter(self.weight)
        s = self._source
        return api.conv2d(x, w, bias=self.bias, stride=s._stride,
                          padding=s._padding, dilation=s._dilation,
                          groups=s._groups)
