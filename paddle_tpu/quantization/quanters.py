"""Fake quanters: simulated quantization inside the training graph
(reference: python/paddle/quantization/quanters/abs_max.py
FakeQuanterWithAbsMaxObserver).

Straight-through estimator: rounding happens on detached values; the
quantize-dequantize delta is re-applied as an additive constant so gradients
flow through unchanged (the reference implements the same STE inside the
fake_quantize CUDA kernels).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops import api as F


def fake_quant_dequant(x: Tensor, scale: float, bits: int = 8) -> Tensor:
    bound = float(2 ** (bits - 1) - 1)
    # scale may be a traced array (QAT inside a compiled step)
    s = (jnp.maximum(scale, 1e-8) if hasattr(scale, "dtype")
         else max(scale, 1e-8)) / bound
    q = jnp.clip(jnp.round(x._value / s), -bound, bound) * s
    delta = Tensor(q - x._value)  # detached STE correction
    delta.stop_gradient = True
    return x + delta


class FakeQuanterWithAbsMax:
    def __init__(self, quant_bits: int = 8, moving_rate: float = 0.9):
        self.quant_bits = quant_bits
        self.moving_rate = moving_rate
        self._scale = None

    def __call__(self, x: Tensor) -> Tensor:
        import jax

        m = jnp.max(jnp.abs(x._value))
        if isinstance(m, jax.core.Tracer):
            # inside a compiled step (TrainStep/jit): the moving average is
            # python state and cannot update per traced call — use the
            # current batch's absmax (stop-gradient, standard QAT inside
            # graphs); the eager path keeps the EMA
            scale = jax.lax.stop_gradient(m)
            return fake_quant_dequant(x, scale, self.quant_bits)
        m = float(m)
        if self._scale is None:
            self._scale = m
        else:
            self._scale = self.moving_rate * self._scale + (1 - self.moving_rate) * m
        return fake_quant_dequant(x, self._scale, self.quant_bits)

    def scales(self):
        return self._scale


class BaseQuanter:
    """Abstract quanter interface (reference
    paddle/quantization/factory.py BaseQuanter): __call__ fake-quantizes;
    scales()/zero_points() expose the learned quantization params."""

    def __call__(self, x):
        raise NotImplementedError

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return None


def quanter(name):
    """Class decorator registering a quanter factory under `name`
    (reference quantization/factory.py quanter): the QuantConfig refers to
    registered quanters by name."""
    def deco(cls):
        _QUANTER_REGISTRY[name] = cls
        return cls

    return deco


_QUANTER_REGISTRY = {"FakeQuanterWithAbsMax": FakeQuanterWithAbsMax}
