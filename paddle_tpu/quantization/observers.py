"""Observers: collect activation/weight ranges (reference:
python/paddle/quantization/observers/abs_max.py etc.)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor


class BaseObserver:
    def __init__(self, quant_bits: int = 8):
        self.quant_bits = quant_bits
        self._scale = None

    def observe(self, x: Tensor):
        raise NotImplementedError

    def scales(self):
        return self._scale

    def bound(self):
        return float(2 ** (self.quant_bits - 1) - 1)

    def quant_axis(self):
        return -1


class AbsmaxObserver(BaseObserver):
    """Running max of |x| (reference: observers/abs_max.py)."""

    def observe(self, x: Tensor):
        m = float(jnp.max(jnp.abs(x._value)))
        self._scale = m if self._scale is None else max(self._scale, m)
        return x


class EMAObserver(BaseObserver):
    """Exponential moving average of per-batch absmax (the reference's
    moving_average_abs_max)."""

    def __init__(self, quant_bits: int = 8, moving_rate: float = 0.9):
        super().__init__(quant_bits)
        self.moving_rate = moving_rate

    def observe(self, x: Tensor):
        m = float(jnp.max(jnp.abs(x._value)))
        if self._scale is None:
            self._scale = m
        else:
            self._scale = self.moving_rate * self._scale + (1 - self.moving_rate) * m
        return x
