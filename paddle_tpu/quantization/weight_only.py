"""Weight-only int8 quantization for serving (reference: the int8 variant
of the fused decoder — paddle/fluid/operators/fused/
fused_multi_transformer_int8_op.cu — plus python/paddle quantization's
weight_only_linear pass).

TPU-native shape: per-output-channel absmax int8 weights dequantized in
the matmul epilogue (ops/kernels/quant.py weight_only_matmul). Quantized
weights/scales are registered BUFFERS, so the compiled decode step
(models/generation.py swaps parameters AND buffers) runs straight off the
int8 tables — 4x less HBM traffic for the weight stream, which is the
decode-phase bottleneck.
"""
from __future__ import annotations

import types
from typing import List

from ..core import flags as _flags
from ..core.tensor import Tensor
from ..ops import api

_flags.define_flag(
    "weight_only_dequant_cache", "auto",
    "Hoist int8 weight-only dequantization out of the decode hot loop by "
    "caching a scale-folded fp table per quantized layer (registered buffer "
    "'dequant_weight'). 'auto' enables it on backends with no int8 GEMM "
    "(everything but TPU), where the per-call convert made int8 decode "
    "SLOWER than fp (DECODEBENCH_r05); 'on'/'off' force it. The int8 tables "
    "remain the storage/wire format either way.")


def _dequant_cache_enabled() -> bool:
    import jax

    v = str(_flags.get_flag("weight_only_dequant_cache")).lower()
    if v in ("on", "true", "1"):
        return True
    if v in ("off", "false", "0"):
        return False
    return jax.default_backend() != "tpu"


def _quantize_linear_like(layer, kind: str) -> None:
    from ..distributed.fleet.mp_layers import all_gather_concat
    from ..distributed.collective import _bound_axis
    from ..ops.kernels.quant import dequantize_weight, quantize_weight_absmax

    import jax.numpy as jnp

    compute_dtype = layer.weight._value.dtype
    q, s = quantize_weight_absmax(layer.weight._value)
    # drop the fp parameter; register int8 + scales as buffers so the
    # generation/TrainStep functional swap carries them
    layer._parameters.pop("weight", None)
    layer.weight = None
    layer.register_buffer("quant_weight", Tensor(q))
    layer.register_buffer("quant_scales", Tensor(s.astype(jnp.float32)))
    use_cache = _dequant_cache_enabled()
    if use_cache:
        # CPU fast path: one scale-folded dequant pass now, so every decode
        # step runs the identical fp GEMM the unquantized model runs (the
        # per-call convert was the DECODEBENCH_r05 regression). Registered
        # as a buffer so compiled decode programs stream it like any weight.
        layer.register_buffer(
            "dequant_weight",
            Tensor(dequantize_weight(q, s, dtype=compute_dtype)))
    # the int8 tables inherit the fp weight's TP layout, or a TP serving
    # run would replicate every table and lose the sharded matmul
    from ..distributed.mesh import annotate_param
    from jax.sharding import PartitionSpec as P

    if kind == "column":
        annotate_param(layer.quant_weight, P(None, "mp"))
        annotate_param(layer.quant_scales, P("mp"))
        if use_cache:
            annotate_param(layer.dequant_weight, P(None, "mp"))
    elif kind == "row":
        annotate_param(layer.quant_weight, P("mp", None))
        annotate_param(layer.quant_scales, P())
        if use_cache:
            annotate_param(layer.dequant_weight, P("mp", None))

    def _wom(self, x, bias):
        return api.weight_only_matmul(
            x, self.quant_weight, self.quant_scales, bias,
            dequant=getattr(self, "dequant_weight", None))

    if kind == "column":
        def fwd(self, x):
            out = _wom(self, x, self.bias)
            if self.gather_output and (_bound_axis(self.group) is not None):
                out = all_gather_concat(out, axis=-1, group=self.group)
            return out
    elif kind == "row":
        def fwd(self, x):
            from ..distributed.collective import all_reduce

            axis = _bound_axis(self.group) if self.group is not None else None
            if axis is None:
                return _wom(self, x, self.bias)
            out = _wom(self, x, None)
            out = all_reduce(out, group=self.group)
            if self.bias is not None:
                out = out + self.bias
            return out
    else:  # plain linear
        def fwd(self, x):
            return _wom(self, x, self.bias)

    layer.forward = types.MethodType(fwd, layer)
    layer._weight_only_quantized = True


def _quantize_tied_head(model, emb_weight) -> None:
    """Weight-only int8 for the TIED LM head (GPT-style `h @ wte.weight^T`).

    The head projection is the single biggest GEMM of a decode step
    (hidden x vocab) and the tied form runs it TRANSPOSED — which XLA:CPU
    executes ~5x slower than the straight [in, out] layout (measured at the
    decodebench head shape). Quantizing the head stores the int8 table (and
    its scale-folded dequant cache) PRE-TRANSPOSED as [hidden, vocab]: the
    int8 model's head streams 4x fewer HBM bytes on TPU and runs the fast
    GEMM layout everywhere. The embedding lookup keeps the fp table."""
    import jax.numpy as jnp

    from ..distributed.mesh import annotate_param
    from ..ops.kernels.quant import dequantize_weight, quantize_weight_absmax
    from jax.sharding import PartitionSpec as P

    compute_dtype = emb_weight._value.dtype
    wt = emb_weight._value.T  # [hidden, vocab] projection view
    q, s = quantize_weight_absmax(wt)  # per-vocab-column scales
    model.register_buffer("head_quant_weight", Tensor(q))
    model.register_buffer("head_quant_scales", Tensor(s.astype(jnp.float32)))
    # vocab is the output dim -> column-parallel layout over 'mp'
    annotate_param(model.head_quant_weight, P(None, "mp"))
    annotate_param(model.head_quant_scales, P("mp"))
    if _dequant_cache_enabled():
        model.register_buffer(
            "head_dequant_weight",
            Tensor(dequantize_weight(q, s, dtype=compute_dtype)))
        annotate_param(model.head_dequant_weight, P(None, "mp"))

    def _head(self, h):
        return api.weight_only_matmul(
            h, self.head_quant_weight, self.head_quant_scales,
            dequant=getattr(self, "head_dequant_weight", None))

    model._head = types.MethodType(_head, model)
    model._head_weight_only = True


def quantize_for_generation(model, algo: str = "weight_only_int8") -> List[str]:
    """Convert every linear-family sublayer of a (causal LM) model to
    int8 weight-only serving form, in place. Returns the names of the
    quantized sublayers. Embeddings, norms, and biases stay fp (the
    reference int8 decoder does the same)."""
    if algo != "weight_only_int8":
        raise ValueError(f"unsupported algo {algo!r}")
    from ..distributed.fleet.mp_layers import (
        ColumnParallelLinear,
        RowParallelLinear,
    )
    from ..nn import Linear

    done = []
    for name, sub in model.named_sublayers():
        if getattr(sub, "_weight_only_quantized", False):
            continue
        if isinstance(sub, ColumnParallelLinear):
            _quantize_linear_like(sub, "column")
        elif isinstance(sub, RowParallelLinear):
            _quantize_linear_like(sub, "row")
        elif isinstance(sub, Linear):
            _quantize_linear_like(sub, "linear")
        else:
            continue
        done.append(name)
    # tied LM heads bypass the Linear sweep (`h @ wte.weight^T`): quantize
    # the projection view too, or the biggest GEMM of every decode step
    # stays fp (and in the slow transposed layout)
    if not getattr(model, "_head_weight_only", False) \
            and getattr(getattr(model, "config", None),
                        "tie_word_embeddings", False) \
            and hasattr(model, "_head"):
        emb = None
        if hasattr(model, "gpt"):  # GPTForCausalLM
            emb = model.gpt.wte.weight
        elif hasattr(model, "model"):  # LlamaForCausalLM (tied config)
            emb = model.model.embed_tokens.weight
        if emb is not None:
            _quantize_tied_head(model, emb)
            done.append("_head")
    # stale compiled decode programs captured the fp parameter list
    if hasattr(model, "_gen_exec_cache"):
        model._gen_exec_cache.clear()
    return done
