"""Weight-only int8 quantization for serving (reference: the int8 variant
of the fused decoder — paddle/fluid/operators/fused/
fused_multi_transformer_int8_op.cu — plus python/paddle quantization's
weight_only_linear pass).

TPU-native shape: per-output-channel absmax int8 weights dequantized in
the matmul epilogue (ops/kernels/quant.py weight_only_matmul). Quantized
weights/scales are registered BUFFERS, so the compiled decode step
(models/generation.py swaps parameters AND buffers) runs straight off the
int8 tables — 4x less HBM traffic for the weight stream, which is the
decode-phase bottleneck.
"""
from __future__ import annotations

import types
from typing import List

from ..core.tensor import Tensor
from ..ops import api


def _quantize_linear_like(layer, kind: str) -> None:
    from ..distributed.fleet.mp_layers import all_gather_concat
    from ..distributed.collective import _bound_axis
    from ..ops.kernels.quant import quantize_weight_absmax

    import jax.numpy as jnp

    q, s = quantize_weight_absmax(layer.weight._value)
    # drop the fp parameter; register int8 + scales as buffers so the
    # generation/TrainStep functional swap carries them
    layer._parameters.pop("weight", None)
    layer.weight = None
    layer.register_buffer("quant_weight", Tensor(q))
    layer.register_buffer("quant_scales", Tensor(s.astype(jnp.float32)))
    # the int8 tables inherit the fp weight's TP layout, or a TP serving
    # run would replicate every table and lose the sharded matmul
    from ..distributed.mesh import annotate_param
    from jax.sharding import PartitionSpec as P

    if kind == "column":
        annotate_param(layer.quant_weight, P(None, "mp"))
        annotate_param(layer.quant_scales, P("mp"))
    elif kind == "row":
        annotate_param(layer.quant_weight, P("mp", None))
        annotate_param(layer.quant_scales, P())

    if kind == "column":
        def fwd(self, x):
            out = api.weight_only_matmul(x, self.quant_weight,
                                         self.quant_scales, self.bias)
            if self.gather_output and (_bound_axis(self.group) is not None):
                out = all_gather_concat(out, axis=-1, group=self.group)
            return out
    elif kind == "row":
        def fwd(self, x):
            from ..distributed.collective import all_reduce

            axis = _bound_axis(self.group) if self.group is not None else None
            if axis is None:
                return api.weight_only_matmul(x, self.quant_weight,
                                              self.quant_scales, self.bias)
            out = api.weight_only_matmul(x, self.quant_weight,
                                         self.quant_scales, None)
            out = all_reduce(out, group=self.group)
            if self.bias is not None:
                out = out + self.bias
            return out
    else:  # plain linear
        def fwd(self, x):
            return api.weight_only_matmul(x, self.quant_weight,
                                          self.quant_scales, self.bias)

    layer.forward = types.MethodType(fwd, layer)
    layer._weight_only_quantized = True


def quantize_for_generation(model, algo: str = "weight_only_int8") -> List[str]:
    """Convert every linear-family sublayer of a (causal LM) model to
    int8 weight-only serving form, in place. Returns the names of the
    quantized sublayers. Embeddings, norms, and biases stay fp (the
    reference int8 decoder does the same)."""
    if algo != "weight_only_int8":
        raise ValueError(f"unsupported algo {algo!r}")
    from ..distributed.fleet.mp_layers import (
        ColumnParallelLinear,
        RowParallelLinear,
    )
    from ..nn import Linear

    done = []
    for name, sub in model.named_sublayers():
        if getattr(sub, "_weight_only_quantized", False):
            continue
        if isinstance(sub, ColumnParallelLinear):
            _quantize_linear_like(sub, "column")
        elif isinstance(sub, RowParallelLinear):
            _quantize_linear_like(sub, "row")
        elif isinstance(sub, Linear):
            _quantize_linear_like(sub, "linear")
        else:
            continue
        done.append(name)
    # stale compiled decode programs captured the fp parameter list
    if hasattr(model, "_gen_exec_cache"):
        model._gen_exec_cache.clear()
    return done
