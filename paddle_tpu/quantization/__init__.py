"""paddle.quantization analog (reference: python/paddle/quantization/ —
QuantConfig, QAT/PTQ entry points, observers, quanters; backed by
quantize_linear/dequantize_linear phi kernels).

TPU-native: fake-quant is simulated in bf16/fp32 arithmetic (quantize ->
round -> dequantize stays inside the compiled graph, so XLA folds it into the
surrounding matmuls); int8 *execution* is an XLA lowering concern
(int8 dot_general on MXU), reached through the same scale metadata this
module produces.
"""
from .config import QuantConfig  # noqa: F401
from .observers import AbsmaxObserver, BaseObserver, EMAObserver  # noqa: F401
from .qat import QAT  # noqa: F401
from .ptq import PTQ  # noqa: F401
from .quanters import BaseQuanter, FakeQuanterWithAbsMax, quanter  # noqa: F401
from .layers import QuantedLinear, QuantedConv2D  # noqa: F401

__all__ = [
    "QuantConfig",
    "QAT",
    "PTQ",
    "BaseObserver",
    "AbsmaxObserver",
    "EMAObserver",
    "FakeQuanterWithAbsMax",
    "QuantedLinear",
    "QuantedConv2D",
]

from .weight_only import quantize_for_generation  # noqa: E402,F401
