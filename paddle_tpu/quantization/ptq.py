"""Post-training quantization (reference: python/paddle/quantization/ptq.py).

PTQ.quantize installs observers via forward-post hooks; after calibration
batches run, convert() computes scales and leaves them on the layers.
"""
from __future__ import annotations

from ..nn.layer import Layer
from ..nn.layers import Conv2D, Linear
from .config import QuantConfig
from .observers import AbsmaxObserver


class PTQ:
    def __init__(self, config: QuantConfig):
        self.config = config
        self._observers = []

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        for name, sub in model.named_sublayers():
            if isinstance(sub, (Linear, Conv2D)) and self.config.needs_quant(sub, name):
                a, w = self.config.get_config(sub, name)
                obs = (a or AbsmaxObserver)()
                sub._ptq_observer = obs
                self._observers.append((sub, obs))
                hook = self._make_hook(obs)
                sub.register_forward_post_hook(hook)
        return model

    @staticmethod
    def _make_hook(obs):
        def hook(layer, inputs, outputs):
            obs.observe(outputs if not isinstance(outputs, tuple) else outputs[0])
            return outputs

        return hook

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        for sub, obs in self._observers:
            sub.activation_scale = obs.scales()
            if getattr(sub, "weight", None) is not None:
                w_obs = AbsmaxObserver()
                w_obs.observe(sub.weight)
                sub.weight_scale = w_obs.scales()
        return model
