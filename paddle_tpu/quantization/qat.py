"""Quantization-aware training (reference: python/paddle/quantization/qat.py).

QAT.quantize(model) swaps quantizable sublayers for their Quanted*
counterparts in place (the reference rewrites the layer tree the same way);
convert() strips quanters for export, leaving collected scales on the layer.
"""
from __future__ import annotations

from ..nn.layer import Layer
from ..nn.layers import Conv2D, Linear
from .config import QuantConfig
from .layers import QuantedConv2D, QuantedLinear

_DEFAULT_MAPPING = {Linear: QuantedLinear, Conv2D: QuantedConv2D}


class QAT:
    def __init__(self, config: QuantConfig):
        self.config = config

    def _wrap(self, layer, name=None):
        a, w = self.config.get_config(layer, name)
        # user mappings take precedence over the generic defaults — a
        # Linear SUBCLASS registered by the user (e.g. a tensor-parallel
        # linear) must not be shadowed by isinstance(layer, Linear)
        user = getattr(self.config, "_qat_mapping", {})
        for mapping in (user, _DEFAULT_MAPPING):
            for src, dst in mapping.items():
                if isinstance(layer, src):
                    return dst(layer,
                               activation_quanter=a() if a else None,
                               weight_quanter=w() if w else None)
        return None

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        for name, sub in list(model._sub_layers.items()):
            if self.config.needs_quant(sub, name):
                wrapped = self._wrap(sub, name)
                if wrapped is not None:
                    model._sub_layers[name] = wrapped
                    continue
            self.quantize(sub, inplace=True)
        return model

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        """Fold quanters away for inference export; scales stay as attrs."""
        for name, sub in list(model._sub_layers.items()):
            if isinstance(sub, (QuantedLinear, QuantedConv2D)):
                # keep the quantized wrapper but freeze its quanters' scales
                sub.weight_scale = sub.weight_quanter.scales()
                sub.activation_scale = sub.activation_quanter.scales()
            else:
                self.convert(sub, inplace=True)
        return model
