"""QuantConfig (reference: python/paddle/quantization/config.py).

Maps layer types / names / full layers to (activation, weight) quantizer
factories, with the same precedence the reference uses: by-layer > by-name >
by-type > global default.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Type

from ..nn.layer import Layer


class QuantConfig:
    def __init__(self, activation=None, weight=None):
        self._global = (activation, weight)
        self._by_type: Dict[type, tuple] = {}
        self._by_name: Dict[str, tuple] = {}
        self._by_layer: Dict[int, tuple] = {}
        self._customized_leaves = []

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._by_layer[id(l)] = (activation, weight)

    def add_name_config(self, layer_name, activation=None, weight=None):
        names = layer_name if isinstance(layer_name, (list, tuple)) else [layer_name]
        for n in names:
            self._by_name[n] = (activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) else [layer_type]
        for t in types:
            self._by_type[t] = (activation, weight)

    def add_qat_layer_mapping(self, source: Type[Layer], target: Type[Layer]):
        self._qat_mapping = getattr(self, "_qat_mapping", {})
        self._qat_mapping[source] = target

    def get_config(self, layer: Layer, name: Optional[str] = None):
        """Resolve (activation_factory, weight_factory) for a layer."""
        if id(layer) in self._by_layer:
            return self._by_layer[id(layer)]
        if name is not None and name in self._by_name:
            return self._by_name[name]
        for t, cfg in self._by_type.items():
            if isinstance(layer, t):
                return cfg
        return self._global

    def needs_quant(self, layer: Layer, name: Optional[str] = None) -> bool:
        a, w = self.get_config(layer, name)
        return a is not None or w is not None
