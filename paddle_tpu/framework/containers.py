"""TensorArray and SelectedRows (reference: paddle LoDTensorArray —
python/paddle/tensor/array.py create_array/array_read/array_write/
array_length — and paddle/phi/core/selected_rows.h + phi
merge_selected_rows kernel).

TPU-native notes: TensorArray is the dynamic-length companion to
lax-structured control flow — under `jit.to_static` tracing, loops are
unrolled or scanned with static trip counts, so the array materializes as a
stacked tensor via .stack(). SelectedRows is the sparse-gradient row format
the reference uses for embedding tables: rows + values, convertible to
dense, with duplicate rows merged by summation (the gradient semantics).
"""
from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops import api as F


class TensorArray:
    """Write-indexed list of same-rank Tensors (LoDTensorArray analog)."""

    def __init__(self, initial: Optional[List[Tensor]] = None):
        self._items: List[Optional[Tensor]] = list(initial or [])

    def write(self, index: int, value: Tensor) -> "TensorArray":
        i = int(index.item() if isinstance(index, Tensor) else index)
        if i < 0:
            raise ValueError(
                f"array_write index must be non-negative, got {i} (negative "
                "python indexing would silently clobber existing slots)")
        if i < len(self._items):
            self._items[i] = value
        else:
            self._items.extend([None] * (i - len(self._items)))
            self._items.append(value)
        return self

    def read(self, index) -> Tensor:
        i = int(index.item() if isinstance(index, Tensor) else index)
        v = self._items[i]
        if v is None:
            raise IndexError(f"TensorArray slot {i} was never written")
        return v

    def append(self, value: Tensor) -> "TensorArray":
        self._items.append(value)
        return self

    def __len__(self):
        return len(self._items)

    def __iter__(self):
        return iter(self._items)

    def _dense_items(self, op):
        holes = [i for i, v in enumerate(self._items) if v is None]
        if holes:
            raise ValueError(
                f"TensorArray.{op}: slots {holes} were never written — "
                "silently dropping holes would misalign positions")
        return list(self._items)

    def stack(self, axis: int = 0) -> Tensor:
        return F.stack(self._dense_items("stack"), axis=axis)

    def concat(self, axis: int = 0) -> Tensor:
        return F.concat(self._dense_items("concat"), axis=axis)


def create_array(dtype=None, initialized_list=None):
    """paddle.tensor.create_array."""
    return TensorArray(initialized_list)


def array_write(x: Tensor, i, array: Optional[TensorArray] = None):
    """paddle.tensor.array_write."""
    if array is None:
        array = TensorArray()
    return array.write(i, x)


def array_read(array: TensorArray, i) -> Tensor:
    return array.read(i)


def array_length(array: TensorArray):
    # int32: jax's default index width (int64 needs jax_enable_x64 and would
    # warn+truncate anyway); paddle's int64 contract is width-only
    return Tensor(jnp.asarray(len(array), jnp.int32))


class SelectedRows:
    """Sparse row-slice tensor: `rows` index into a [height, ...] dense
    space, `values` holds the selected slices (phi SelectedRows)."""

    def __init__(self, rows, values: Tensor, height: int):
        self.rows = jnp.asarray(
            rows._value if isinstance(rows, Tensor) else rows, jnp.int32)
        self.values = values if isinstance(values, Tensor) else Tensor(values)
        self.height = int(height)

    @property
    def shape(self):
        return (self.height,) + tuple(self.values.shape[1:])

    def to_dense(self) -> Tensor:
        out = jnp.zeros(self.shape, self.values._value.dtype)
        return Tensor(out.at[self.rows].add(self.values._value))

    def merge(self) -> "SelectedRows":
        """phi merge_selected_rows: dedupe rows, summing duplicate slices
        (the embedding sparse-grad accumulation rule)."""
        uniq, inv = jnp.unique(self.rows, return_inverse=True,
                               size=self.rows.shape[0],
                               fill_value=self.height)
        summed = jnp.zeros((uniq.shape[0],) + tuple(self.values.shape[1:]),
                           self.values._value.dtype)
        summed = summed.at[inv].add(self.values._value)
        keep = uniq < self.height
        n = int(jnp.sum(keep))
        return SelectedRows(uniq[:n], Tensor(summed[:n]), self.height)


def merge_selected_rows(x: SelectedRows) -> SelectedRows:
    return x.merge()
