"""paddle.save / paddle.load (reference: python/paddle/framework/io.py:646,888).

Pickles nested containers with tensors converted to numpy, like the reference.
Sharded/async distributed checkpointing lives in distributed/checkpoint.py
(orbax-backed).
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import Parameter


class _TensorPayload:
    def __init__(self, array, is_param, name, trainable=True,
                 stop_gradient=True):
        self.array = array
        self.is_param = is_param
        self.name = name
        self.trainable = trainable
        self.stop_gradient = stop_gradient


def _pack(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._value),
                              isinstance(obj, Parameter), obj.name,
                              trainable=getattr(obj, "trainable", True),
                              stop_gradient=obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):  # namedtuple
        return type(obj)(*(_pack(v) for v in obj))
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        if obj.is_param:
            t = Parameter(obj.array, name=obj.name,
                          trainable=getattr(obj, "trainable", True))
        else:
            t = Tensor(obj.array)
            t.stop_gradient = getattr(obj, "stop_gradient", True)
        return t
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):  # namedtuple
        return type(obj)(*(_unpack(v, return_numpy) for v in obj))
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    """Atomic save: pickle to `path + ".tmp"`, fsync, then os.replace — a
    crash mid-write can never leave a truncated file at the destination
    (the destination either keeps its old content or gets the complete new
    one)."""
    from ..resilience import chaos

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)
        f.flush()
        os.fsync(f.fileno())
        chaos.crash_point("io.save.before_replace")
    os.replace(tmp, path)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)
