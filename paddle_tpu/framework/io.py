"""paddle.save / paddle.load (reference: python/paddle/framework/io.py:646,888).

Pickles nested containers with tensors converted to numpy, like the reference.
Sharded/async distributed checkpointing lives in distributed/checkpoint.py
(orbax-backed).
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import Parameter


class _TensorPayload:
    def __init__(self, array, is_param, name):
        self.array = array
        self.is_param = is_param
        self.name = name


def _pack(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._value), isinstance(obj, Parameter), obj.name)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        t = Parameter(obj.array, name=obj.name) if obj.is_param else Tensor(obj.array)
        return t
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)
