from .io import load, save  # noqa: F401

from .containers import (  # noqa: F401, E402
    SelectedRows,
    TensorArray,
    array_length,
    array_read,
    array_write,
    create_array,
    merge_selected_rows,
)
