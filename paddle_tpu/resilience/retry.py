"""Reusable retry/backoff policy.

Reference: the restart-on-failure loops scattered through the reference
launcher (python/paddle/distributed/launch/controller/ watch/restart) and the
etcd reconnect loops in fleet/elastic — here factored into ONE policy object
with exponential backoff, decorrelated jitter, a wall-clock deadline, and
exception filters, adopted by TCPStore connect, collective-store init, and
DataLoader worker respawn (SURVEY §5.3: preemption-aware restart needs every
transient failure path to retry the same way).

Pure stdlib on purpose: this module is imported by the native layer and by
forked dataloader workers, neither of which may pull in jax.
"""
from __future__ import annotations

import functools
import random
import time
from typing import Callable, Optional, Sequence, Tuple, Type


class RetryError(RuntimeError):
    """All attempts exhausted (or deadline passed); carries the last cause."""

    def __init__(self, message: str, attempts: int, last_exception: BaseException):
        super().__init__(message)
        self.attempts = attempts
        self.last_exception = last_exception


class RetryPolicy:
    """Exponential backoff with jitter, attempt cap, and deadline.

    Args:
        max_attempts: total tries (first call included). <=0 means unlimited
            (the deadline must then bound the loop).
        base_delay: sleep after the first failure (seconds).
        max_delay: backoff ceiling.
        multiplier: backoff growth factor.
        jitter: fraction of the delay randomized away, in [0, 1]. The sleep is
            uniform in [delay*(1-jitter), delay] so the worst case never
            exceeds the deterministic schedule (thundering-herd spread).
        deadline: overall wall-clock budget in seconds; once exceeded no
            further attempt starts.
        retry_on: exception classes considered transient.
        retry_filter: optional predicate(exc) -> bool for finer filtering
            (e.g. retry ConnectionRefusedError but not auth failures).
        sleep: injectable for tests.
    """

    def __init__(
        self,
        max_attempts: int = 3,
        base_delay: float = 0.1,
        max_delay: float = 5.0,
        multiplier: float = 2.0,
        jitter: float = 0.5,
        deadline: Optional[float] = None,
        retry_on: Tuple[Type[BaseException], ...] = (Exception,),
        retry_filter: Optional[Callable[[BaseException], bool]] = None,
        sleep: Callable[[float], None] = time.sleep,
        name: str = "",
    ):
        if not 0.0 <= jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.deadline = deadline
        self.retry_on = tuple(retry_on)
        self.retry_filter = retry_filter
        self._sleep = sleep
        self.name = name
        self._rng = random.Random(0x5EED)  # deterministic spread for tests

    # -- schedule ----------------------------------------------------------
    def delay_for(self, attempt: int) -> float:
        """Backoff before attempt `attempt+1` (attempt is 1-based count of
        failures so far), pre-jitter."""
        d = self.base_delay * (self.multiplier ** max(attempt - 1, 0))
        return min(d, self.max_delay)

    def _jittered(self, delay: float) -> float:
        if self.jitter <= 0.0 or delay <= 0.0:
            return delay
        lo = delay * (1.0 - self.jitter)
        return self._rng.uniform(lo, delay)

    def _retryable(self, exc: BaseException) -> bool:
        if not isinstance(exc, self.retry_on):
            return False
        if self.retry_filter is not None and not self.retry_filter(exc):
            return False
        return True

    # -- execution ---------------------------------------------------------
    def call(self, fn: Callable, *args, **kwargs):
        """Run fn until it succeeds, attempts run out, or the deadline hits."""
        start = time.monotonic()
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(*args, **kwargs)
            except BaseException as exc:  # noqa: BLE001 — filtered below
                if not self._retryable(exc):
                    raise
                out_of_attempts = (self.max_attempts > 0
                                   and attempt >= self.max_attempts)
                delay = self._jittered(self.delay_for(attempt))
                over_deadline = (
                    self.deadline is not None
                    and time.monotonic() - start + delay >= self.deadline)
                if out_of_attempts or over_deadline:
                    label = self.name or getattr(fn, "__name__", "call")
                    raise RetryError(
                        f"{label}: giving up after {attempt} attempt(s): "
                        f"{type(exc).__name__}: {exc}", attempt, exc) from exc
                self._sleep(delay)

    def backoff(self, attempt: int):
        """Sleep the jittered backoff for `attempt` (1-based failure count).
        For callers that drive their own recovery loop (e.g. worker respawn)
        but want this policy's pacing."""
        self._sleep(self._jittered(self.delay_for(attempt)))

    def jittered_delay(self, attempt: int) -> float:
        """The jittered backoff for `attempt` WITHOUT sleeping — for
        callers that schedule recovery on their own event loop (e.g. the
        fleet replica supervisor arming a respawn deadline) rather than
        blocking a thread on it."""
        return self._jittered(self.delay_for(attempt))

    def wrap(self, fn: Callable) -> Callable:
        """Decorator form: `resilient_fn = policy.wrap(fn)`."""

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            return self.call(fn, *args, **kwargs)

        return inner

    def __repr__(self):  # pragma: no cover
        return (f"RetryPolicy(max_attempts={self.max_attempts}, "
                f"base_delay={self.base_delay}, deadline={self.deadline})")


def retrying(policy: Optional[RetryPolicy] = None, **kwargs) -> Callable:
    """`@retrying(max_attempts=5)` decorator sugar over RetryPolicy.wrap."""
    pol = policy or RetryPolicy(**kwargs)

    def deco(fn):
        return pol.wrap(fn)

    return deco
