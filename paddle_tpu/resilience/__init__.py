"""Fault-tolerant training runtime.

Pieces (wired together by ResilientTrainer, each usable alone):
  - CheckpointManager  : crash-consistent commit (tmp dir -> manifest with
                         per-array checksums -> atomic rename), keep-last-N
                         GC that never drops the last valid checkpoint, and
                         restore_latest() with corruption fallback.
  - PreemptionHandler  : SIGTERM/SIGINT + elastic-membership loss latched
                         into one flag the training loop polls.
  - RetryPolicy        : backoff/jitter/deadline retries, adopted by the
                         TCPStore connect, collective-store init, and the
                         DataLoader worker respawn path.
  - chaos              : fault-injection harness (crash points inside
                         checkpoint writes, NaN batch poisoning, worker
                         kills, fake preemption signals) backing the tests
                         and tools/faultbench.py.
"""
from __future__ import annotations

from . import chaos  # noqa: F401
from .checkpoint_manager import (  # noqa: F401
    CheckpointCorrupt, CheckpointManager, RestoredCheckpoint,
)
from .preemption import PreemptionHandler  # noqa: F401
from .retry import RetryError, RetryPolicy, retrying  # noqa: F401

__all__ = [
    "CheckpointManager", "CheckpointCorrupt", "RestoredCheckpoint",
    "PreemptionHandler", "RetryPolicy", "RetryError", "retrying",
    "ResilientTrainer", "ElasticTrainer", "MicroBatchRebalancer", "chaos",
]


def __getattr__(name):
    # ResilientTrainer / ElasticTrainer pull in jit.trainer (and with it
    # the whole nn/opt stack); resolve them lazily so
    # `from paddle_tpu.resilience import chaos` stays import-light for
    # forked dataloader workers.
    if name == "ResilientTrainer":
        from .trainer import ResilientTrainer

        return ResilientTrainer
    if name in ("ElasticTrainer", "MicroBatchRebalancer"):
        from . import elastic

        return getattr(elastic, name)
    raise AttributeError(name)
