"""Auto-resuming training loop over the compiled TrainStep.

Ties the resilience pieces together (SURVEY §5.3 "preemption-aware
restart"): the CheckpointManager's crash-consistent save/restore carries
params, optimizer state, the step counter, RNG state, and the dataloader
position; the PreemptionHandler turns SIGTERM / elastic membership loss into
a final synchronized checkpoint + clean exit; the TrainStep NaN guard skips
poisoned steps inside the single compiled program. Restarting the same
script resumes from the latest VALID checkpoint with no manual intervention
— the reference's restart-on-failure launcher semantics, minus the lost
work.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterable, Optional, Sequence, Union

import numpy as np

from . import chaos
from .checkpoint_manager import CheckpointManager
from .preemption import PreemptionHandler
from ..observability import anomaly as _anomaly
from ..observability import flight_recorder as _flight
from ..observability import serve as _serve
from ..observability import telemetry as _telemetry

__all__ = ["ResilientTrainer"]


def _poison_first_float(batch):
    """Copy `batch` with a NaN planted in its first float array leaf (host
    side — the compiled program then sees a genuinely poisoned gradient)."""
    from ..core.tensor import Tensor

    done = [False]

    def rec(obj):
        if done[0]:
            return obj
        if isinstance(obj, Tensor):
            arr = np.array(obj.numpy())
            if np.issubdtype(arr.dtype, np.floating) and arr.size:
                arr.flat[0] = np.nan
                done[0] = True
                return Tensor(arr)
            return obj
        if isinstance(obj, np.ndarray):
            if np.issubdtype(obj.dtype, np.floating) and obj.size:
                arr = obj.copy()
                arr.flat[0] = np.nan
                done[0] = True
                return arr
            return obj
        if isinstance(obj, (list, tuple)):
            out = [rec(v) for v in obj]
            return tuple(out) if isinstance(obj, tuple) else out
        if isinstance(obj, dict):
            return {k: rec(v) for k, v in obj.items()}
        return obj

    return rec(batch)


class ResilientTrainer:
    """TrainStep wrapper with periodic crash-consistent checkpoints,
    SIGTERM-clean exits, NaN-step skipping, and automatic resume.

    Args:
        model / loss_fn / optimizer: as for jit.trainer.TrainStep.
        manager: CheckpointManager (or a root path, turned into one).
        save_every: checkpoint cadence in global steps (0 = only final).
        preemption: PreemptionHandler to poll between steps; created (and
            installed by run()) when None.
        nan_guard: compile the NaN/Inf step-guard into the train step.
        backoff: optional amp.LossScaleBackoff (or any object with
            on_step(skipped: bool)) fed the guard verdict every step.
        anomaly_engine: observability.AnomalyEngine fed each completed step
            record; built from flags (FLAGS_anomaly) when None.
        cluster: observability.ClusterTelemetry — when set, every step
            record is published through the process-group store for rank-0
            aggregation + straggler detection.
        step_kwargs: extra TrainStep kwargs (shardings, mesh, donate).
    """

    def __init__(self, model, loss_fn, optimizer,
                 manager: Union[CheckpointManager, str], *,
                 save_every: int = 100,
                 preemption: Optional[PreemptionHandler] = None,
                 nan_guard: bool = True,
                 backoff=None,
                 anomaly_engine=None,
                 cluster=None,
                 **step_kwargs):
        from ..jit.trainer import TrainStep

        if isinstance(manager, str):
            manager = CheckpointManager(manager)
        self.manager = manager
        self.model = model
        self.optimizer = optimizer
        # Donation is off by default here (callers can still opt back in via
        # step_kwargs): a heap-layout-sensitive XLA:CPU bug (ROADMAP "Carried
        # bugs") can leave the final written-back params aliasing freed donor
        # memory, so a resilient run's whole point — params you can trust
        # after run() returns — is worth the extra in-flight copy.
        step_kwargs.setdefault("donate", False)
        self.step = TrainStep(model, loss_fn, optimizer,
                              nan_guard=nan_guard, **step_kwargs)
        self.save_every = int(save_every)
        self.preemption = preemption
        self.backoff = backoff
        self.anomaly_engine = anomaly_engine
        self.cluster = cluster
        self._epoch = 0
        self._offset = 0  # batches consumed in the current epoch
        self.resumed_from: Optional[int] = None

    # -- state <-> checkpoint ---------------------------------------------
    def _state(self) -> Dict[str, Any]:
        return {
            "params": [p._value for p in self.step.params],
            "buffers": [b._value for b in self.step.buffers],
            "opt_state": self.step.opt_state,
        }

    def _meta(self) -> Dict[str, Any]:
        from ..core import random as _random

        seed, counter = _random.get_rng_state()
        return {
            "step": int(self.step._step_i),
            "opt_step_count": int(self.optimizer._step_count),
            "rng": [int(seed), int(counter)],
            "epoch": int(self._epoch),
            "offset": int(self._offset),
            "skipped_steps": int(self.step.skipped_steps),
            # recorded so restore() can refuse a world-size mismatch loudly
            # instead of silently loading misshaped sharded state
            "world_size": int(self.manager.world_size),
        }

    def save(self):
        """Synchronized checkpoint of everything resume needs."""
        return self.manager.save(self.step._step_i, self._state(),
                                 meta=self._meta())

    def restore(self):
        """Load the latest valid checkpoint into the live training state;
        returns the RestoredCheckpoint or None when starting fresh."""
        import jax.numpy as jnp

        from ..core import random as _random

        restored = self.manager.restore_latest(template=self._state())
        if restored is None:
            return None
        state, meta = restored.state, restored.meta
        saved_world = meta.get("world_size")
        cur_world = int(self.manager.world_size)
        if saved_world is not None and int(saved_world) != cur_world:
            raise RuntimeError(
                f"checkpoint {restored.path} (step {restored.step}) was "
                f"saved at world size {int(saved_world)} but this run has "
                f"world size {cur_world} — refusing to load misshaped "
                f"sharded state. Reshard it explicitly with "
                f"distributed.checkpoint.load_sharded(path, "
                f"target_world_size={cur_world}, target_rank=<rank>), or "
                f"use resilience.elastic.ElasticTrainer, which reforms "
                f"the mesh and reshards automatically on membership "
                f"change.")
        for p, v in zip(self.step.params, state["params"]):
            p._value = jnp.asarray(v)
        for b, v in zip(self.step.buffers, state["buffers"]):
            b._value = jnp.asarray(v)
        self.step.opt_state = _tree_asarray(state["opt_state"])
        self.step._step_i = int(meta.get("step", restored.step))
        self.optimizer._step_count = int(
            meta.get("opt_step_count", self.step._step_i))
        self.step.skipped_steps = int(meta.get("skipped_steps", 0))
        if "rng" in meta:
            _random.set_rng_state(tuple(meta["rng"]))
        self._epoch = int(meta.get("epoch", 0))
        self._offset = int(meta.get("offset", 0))
        self.resumed_from = restored.step
        return restored

    # -- loop --------------------------------------------------------------
    def run(self, batches: Union[Sequence, Callable[[], Iterable]], *,
            epochs: int = 1, resume: bool = True) -> Dict[str, Any]:
        """Train over `batches` (a sequence of batch tuples, or a callable
        returning a fresh iterable per epoch — e.g. ``lambda: dataloader``)
        for `epochs`, checkpointing every `save_every` steps.

        Auto-resumes from the latest valid checkpoint (step counter, RNG,
        epoch/offset replay-skip) when `resume`. Returns a report dict with
        status "completed" or "preempted"; on preemption a final checkpoint
        is committed before returning so the next run() continues cleanly.
        """
        if resume:
            self.restore()
        report = {
            "status": "completed",
            "steps_run": 0,
            "steps_skipped_start": int(self.step.skipped_steps),
            "resumed_from": self.resumed_from,
        }
        preempt = self.preemption
        installed_here = False
        if preempt is None:
            preempt = self.preemption = PreemptionHandler()
        if not preempt._installed:
            preempt.install()
            installed_here = True
        # per-step telemetry (observability/): this loop owns the phases the
        # compiled step can't see — host data wait before the step, blocking
        # checkpoint time after it
        tele = _telemetry.get_telemetry() if _telemetry.enabled() else None
        if tele is not None:
            if self.anomaly_engine is None:
                self.anomaly_engine = _anomaly.from_flags()
            if self.anomaly_engine is not None:
                _serve.set_health_engine(self.anomaly_engine)
            _serve.maybe_start_from_flags()
        try:
            while self._epoch < epochs:
                it = iter(batches() if callable(batches) else batches)
                i = -1
                while True:
                    t_data = time.perf_counter()
                    try:
                        batch = next(it)
                    except StopIteration:
                        break
                    i += 1
                    if i < self._offset:
                        continue  # replayed prefix of a resumed epoch
                    if tele is not None:
                        tele.pre_phase("data", time.perf_counter() - t_data)
                    if preempt.requested:
                        self._timed_save(tele)
                        report["status"] = "preempted"
                        report["preempt_reason"] = preempt.reason
                        return self._finish(report)
                    gstep = self.step._step_i
                    if chaos.should_poison(gstep):
                        batch = _poison_first_float(batch)
                        chaos.note_poisoned(gstep)
                    loss = self.step(*batch)
                    report["steps_run"] += 1
                    report["last_loss"] = float(np.asarray(loss.numpy()))
                    if self.backoff is not None:
                        self.backoff.on_step(self.step.last_skipped)
                    if tele is not None:
                        rec = tele.last_record()
                        if rec is not None:
                            if self.anomaly_engine is not None:
                                self.anomaly_engine.observe(rec)
                            if self.cluster is not None:
                                self.cluster.publish(rec)
                    self._offset = i + 1
                    if self.save_every and \
                            self.step._step_i % self.save_every == 0:
                        self._timed_save(tele)
                self._epoch += 1
                self._offset = 0
            self._timed_save(tele)
            return self._finish(report)
        except BaseException as e:
            # black-box forensics for anything escaping the loop (chaos
            # InjectedCrash included); the exception itself still propagates
            _flight.on_exception(e)
            raise
        finally:
            if installed_here:
                preempt.uninstall()

    def _timed_save(self, tele):
        t0 = time.perf_counter()
        out = self.save()
        if tele is not None:
            tele.post_phase("save", time.perf_counter() - t0)
        return out

    def _finish(self, report: Dict[str, Any]) -> Dict[str, Any]:
        self.manager.wait()  # run() must not return before the final commit
        self.step.sync_to_optimizer()
        # Donation-UAF mitigation: the compiled train step donates its
        # param/opt-state buffers, and on XLA:CPU a heap-layout-sensitive
        # bug (see ROADMAP "Carried bugs") can leave the FINAL written-back
        # param arrays aliasing freed donor memory — reads after run()
        # return garbage without tripping jax's deleted-array guard. Settle
        # every in-flight donation, then rematerialize each param as a
        # fresh buffer so nothing returned from run() aliases donated HBM.
        import jax
        import jax.numpy as jnp
        for p in self.step.params:
            p._value = jnp.array(jax.block_until_ready(p._value))
        report["step"] = int(self.step._step_i)
        report["steps_skipped"] = (int(self.step.skipped_steps)
                                   - report.pop("steps_skipped_start"))
        report["steps_skipped_total"] = int(self.step.skipped_steps)
        if _telemetry.enabled():
            tele = _telemetry.get_telemetry()
            tele.finalize()  # flush the staged record + Prometheus textfile
            report["telemetry"] = tele.summary()
        return report


def _tree_asarray(tree):
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(jnp.asarray, tree)
