"""Fault-injection harness ("chaos monkey") for the resilience subsystem.

Production code calls `crash_point("name")` at carefully chosen spots in
checkpoint writes and file commits; tests and tools/faultbench.py arm those
points with `inject_crash(...)` to simulate a process dying mid-save. The
harness also poisons training batches with NaNs (to exercise the compiled
NaN step-guard), kills DataLoader worker processes, and delivers fake
preemption signals — the machinery that lets tier-1 tests PROVE the
crash-consistency and auto-resume claims instead of asserting them.

Pure stdlib: imported by framework/io.py and forked workers; must not pull
in jax.
"""
from __future__ import annotations

import os
import signal as _signal
import threading
from typing import Dict, Iterable, Optional

__all__ = [
    "InjectedCrash", "inject_crash", "crash_point", "clear", "armed",
    "poison_steps", "should_poison", "note_poisoned", "kill_worker",
    "fake_preemption", "stats", "reset_stats", "scope",
    "kill_rank", "should_kill_rank", "note_rank_killed",
    "slow_rank", "rank_delay",
    "kill_process", "hang_process", "resume_process", "sigstop_supported",
    "StorePartitionProxy",
]


class InjectedCrash(RuntimeError):
    """Raised at an armed crash point; simulates the process dying there."""

    def __init__(self, point: str):
        super().__init__(f"injected crash at {point!r}")
        self.point = point


_lock = threading.Lock()
_crash_points: Dict[str, dict] = {}   # name -> {"after": int, "mode": str}
_poison_steps: set = set()
_rank_kills: Dict[int, int] = {}      # member id -> kill at global step
_rank_delays: Dict[int, float] = {}   # member id -> extra seconds per step

stats = {
    "crashes_injected": 0,
    "steps_poisoned": 0,
    "workers_killed": 0,
    "signals_sent": 0,
    "ranks_killed": 0,
    "processes_killed": 0,
    "processes_hung": 0,
    "processes_resumed": 0,
    "partitions_started": 0,
}


def reset_stats():
    for k in stats:
        stats[k] = 0


def clear():
    """Disarm every crash point and poison schedule (stats are kept)."""
    with _lock:
        _crash_points.clear()
        _poison_steps.clear()
        _rank_kills.clear()
        _rank_delays.clear()


def armed(point: Optional[str] = None) -> bool:
    with _lock:
        if point is None:
            return bool(_crash_points)
        return point in _crash_points


def inject_crash(point: str, after: int = 0, mode: str = "raise"):
    """Arm `point`: the (after+1)-th hit fires. mode="raise" raises
    InjectedCrash (in-process crash simulation — the write path genuinely
    stops mid-flight); mode="exit" calls os._exit(23) for subprocess tests
    where not even finally-blocks may run."""
    if mode not in ("raise", "exit"):
        raise ValueError(f"unknown crash mode {mode!r}")
    with _lock:
        _crash_points[point] = {"after": int(after), "mode": mode}


def crash_point(name: str):
    """Instrumentation hook called by production code. No-op unless armed."""
    with _lock:
        entry = _crash_points.get(name)
        if entry is None:
            return
        if entry["after"] > 0:
            entry["after"] -= 1
            return
        del _crash_points[name]  # one-shot: the "process" died here once
        mode = entry["mode"]
        stats["crashes_injected"] += 1
    if mode == "exit":  # pragma: no cover — used by subprocess tests only
        os._exit(23)
    raise InjectedCrash(name)


# -- NaN poisoning ----------------------------------------------------------

def poison_steps(steps: Iterable[int]):
    """Schedule global step indices whose batch gets a NaN injected (the
    ResilientTrainer consults this before each compiled step)."""
    with _lock:
        _poison_steps.update(int(s) for s in steps)


def should_poison(step: int) -> bool:
    with _lock:
        return int(step) in _poison_steps


def note_poisoned(step: int):
    with _lock:
        _poison_steps.discard(int(step))
        stats["steps_poisoned"] += 1


# -- elastic rank faults ----------------------------------------------------

def kill_rank(member: int, at_step: int):
    """Arm a rank kill: the elastic trainer checks should_kill_rank() at
    the top of each global step and, once armed-and-reached, the member
    stops heartbeating and exits its loop WITHOUT a left marker — from the
    survivors' perspective an unannounced crash whose lease expires."""
    with _lock:
        _rank_kills[int(member)] = int(at_step)


def should_kill_rank(member: int, step: int) -> bool:
    with _lock:
        at = _rank_kills.get(int(member))
        return at is not None and int(step) >= at


def note_rank_killed(member: int):
    """The member died; disarm its kill (one-shot) and count it."""
    with _lock:
        _rank_kills.pop(int(member), None)
        stats["ranks_killed"] += 1


def slow_rank(member: int, delay_s: float):
    """Arm a per-step straggler delay for one member (rank_delay() is
    added to its step wall time by the elastic trainer) — exercises the
    micro-batch rebalancer without ejecting anyone. delay_s <= 0 disarms."""
    with _lock:
        if float(delay_s) <= 0:
            _rank_delays.pop(int(member), None)
        else:
            _rank_delays[int(member)] = float(delay_s)


def rank_delay(member: int) -> float:
    with _lock:
        return _rank_delays.get(int(member), 0.0)


# -- process-level faults ---------------------------------------------------

def kill_worker(pool, wid: int = 0, sig: int = _signal.SIGKILL):
    """Hard-kill one DataLoader worker process (io/worker.py WorkerPool)."""
    proc = pool.procs[wid]
    os.kill(proc.pid, sig)
    stats["workers_killed"] += 1


def fake_preemption(sig: int = _signal.SIGTERM):
    """Deliver a real signal to this process — exercises the installed
    PreemptionHandler exactly like a TPU maintenance-event SIGTERM."""
    stats["signals_sent"] += 1
    os.kill(os.getpid(), sig)


def _pid_of(proc_or_pid) -> int:
    return int(getattr(proc_or_pid, "pid", proc_or_pid))


def sigstop_supported() -> bool:
    """Can this platform hard-freeze a process (SIGSTOP/SIGCONT)? The
    faultbench hang scenarios skip gracefully where it can't."""
    return (os.name == "posix" and hasattr(_signal, "SIGSTOP")
            and hasattr(_signal, "SIGCONT"))


def kill_process(proc_or_pid):
    """SIGKILL a real OS process (process replica / elastic rank child):
    no cleanup handlers run, heartbeats simply stop — the genuine article
    the thread-level kill_rank/kill() only simulate."""
    os.kill(_pid_of(proc_or_pid), _signal.SIGKILL)
    stats["processes_killed"] += 1


def hang_process(proc_or_pid):
    """SIGSTOP a real OS process: still alive by waitpid (no exit code)
    but silent — heartbeats freeze, so only lease expiry can declare it
    dead. Pair with resume_process() to wake the zombie and exercise
    fence-token rejection."""
    if not sigstop_supported():
        raise RuntimeError("SIGSTOP/SIGCONT not supported on this platform")
    os.kill(_pid_of(proc_or_pid), _signal.SIGSTOP)
    stats["processes_hung"] += 1


def resume_process(proc_or_pid):
    """SIGCONT a hung process — the revived zombie must fence itself out
    (see serving/fleet_proc.py) rather than serve stale state."""
    if not sigstop_supported():
        raise RuntimeError("SIGSTOP/SIGCONT not supported on this platform")
    os.kill(_pid_of(proc_or_pid), _signal.SIGCONT)
    stats["processes_resumed"] += 1


class StorePartitionProxy:
    """Network-partition shim for one store member: a real TCP forwarding
    proxy a victim's TCPStore client connects THROUGH, so its store
    traffic can be stalled (held, delivered after heal — the classic
    partition) or dropped (connections severed) for a window without
    touching the process itself. Lease expiry and the supervisor's
    heal-without-respawn grace path get exercised with everyone alive.

    Pure stdlib sockets + threads; forwarding is byte-level so it works
    for any store protocol."""

    def __init__(self, upstream_host: str, upstream_port: int,
                 listen_host: str = "127.0.0.1"):
        import socket

        self.upstream = (str(upstream_host), int(upstream_port))
        self._gate = threading.Event()   # set = traffic flows
        self._gate.set()
        self._mode = "stall"
        self._open = True
        self._conns = []                 # live socket pairs, for drop mode
        self._conns_lock = threading.Lock()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((listen_host, 0))
        self._srv.listen(16)
        self.host, self.port = self._srv.getsockname()[:2]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="chaos-partition-accept",
            daemon=True)
        self._accept_thread.start()

    # -- forwarding ---------------------------------------------------------
    def _accept_loop(self):
        import socket

        while self._open:
            try:
                cli, _ = self._srv.accept()
            except OSError:
                return
            if not self._open:
                cli.close()
                return
            try:
                up = socket.create_connection(self.upstream, timeout=10)
            except OSError:
                cli.close()
                continue
            with self._conns_lock:
                self._conns.append((cli, up))
            for a, b in ((cli, up), (up, cli)):
                threading.Thread(target=self._pump, args=(a, b),
                                 name="chaos-partition-pump",
                                 daemon=True).start()

    def _pump(self, src, dst):
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                # the partition gate: while down, bytes are HELD here
                # (stall mode) — delivered when the partition heals, like
                # a switch buffering across a link flap
                while not self._gate.wait(timeout=0.5):
                    if not self._open:
                        return
                dst.sendall(data)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                try:
                    s.shutdown(2)
                except OSError:
                    pass

    # -- chaos controls -----------------------------------------------------
    def partition(self, duration_s: float = 0.0, mode: str = "stall"):
        """Cut the victim's store traffic. mode="stall" holds bytes until
        heal(); mode="drop" severs every live connection (a client with a
        single persistent socket sees hard errors). duration_s > 0 arms a
        timer that heals automatically."""
        if mode not in ("stall", "drop"):
            raise ValueError(f"unknown partition mode {mode!r}")
        self._mode = mode
        stats["partitions_started"] += 1
        self._gate.clear()
        if mode == "drop":
            with self._conns_lock:
                conns, self._conns = self._conns, []
            for cli, up in conns:
                for s in (cli, up):
                    try:
                        s.close()
                    except OSError:
                        pass
        if duration_s > 0:
            t = threading.Timer(float(duration_s), self.heal)
            t.daemon = True
            t.start()

    def heal(self):
        """Restore traffic (held bytes from a stall flush through)."""
        self._gate.set()

    @property
    def partitioned(self) -> bool:
        return not self._gate.is_set()

    def close(self):
        self._open = False
        self._gate.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._conns_lock:
            conns, self._conns = self._conns, []
        for cli, up in conns:
            for s in (cli, up):
                try:
                    s.close()
                except OSError:
                    pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class scope:
    """Context manager: arm injections inside, guaranteed clear() on exit."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        clear()
        return False
