"""Crash-consistent checkpoint manager.

Layout under `root`:

    step_00000042/            committed checkpoint (atomic rename target)
        manifest.json         per-array entries {file, shape, dtype, crc32},
                              structure skeleton, user meta, format version
        arr_0.bin ...         raw array bytes, one file per pytree leaf
    step_00000050.tmp/        in-flight write (never read; GC'd on next save)

Commit protocol (the reference's dist_saver writes rank shards then a
"success" flag file; here the flag is the directory NAME so readers need no
flag-ordering reasoning):

    1. write every array file (fsync each)
    2. write manifest.json.tmp, fsync, os.replace -> manifest.json
    3. os.rename(step_N.tmp, step_N)        <- the commit point
    4. only now GC older checkpoints (keep-last-N, never the last valid one)

A crash at ANY point leaves either a fully committed directory or an ignored
`.tmp` — `save_sharded(overwrite=True)`'s original delete-before-write hazard
(losing the only good checkpoint) cannot happen. `restore_latest()` scans
newest-first, re-verifies every checksum, and falls back to the previous
checkpoint when it finds torn or bit-rotted state.

`backend="orbax"` delegates the array payload to distributed/checkpoint.py's
sharded writer (each host writes its addressable shards) while keeping this
module's tmp-dir commit + manifest + GC around it.

Chaos hooks (resilience/chaos.py) instrument each phase so tests can kill the
write at every interesting spot.
"""
from __future__ import annotations

import json
import os
import re
import shutil
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import chaos
from ..observability.registry import counter as _obs_counter
from ..observability.spans import span as _span

_SAVES = _obs_counter(
    "checkpoint_saves_total",
    "Checkpoint saves by outcome: committed = the atomic rename landed, "
    "failed = the write raised before the commit point.",
    labelnames=("outcome",))

_SYNC_COMMITS = _obs_counter(
    "cluster_ckpt_commits_total",
    "Multi-host synchronized checkpoint commits, by this rank's role "
    "(leader = rank 0 performed the atomic rename after all ranks reported "
    "ready; follower = waited for the leader's committed marker).",
    labelnames=("role",))

_CKPT_KEY_PREFIX = "/pt/ckpt"


def _store_wait_ge(store, key: str, target: int, timeout_s: float) -> int:
    """wait_ge across store flavors: InProcStore takes timeout_s, the native
    TCPStore client carries its own socket timeout."""
    try:
        return store.wait_ge(key, target, timeout_s=timeout_s)
    except TypeError:
        return store.wait_ge(key, target)


def _store_get(store, key: str, timeout_s: float):
    try:
        return store.get(key, blocking=True, timeout_s=timeout_s)
    except TypeError:
        return store.get(key, blocking=True)

__all__ = ["CheckpointManager", "CheckpointCorrupt", "RestoredCheckpoint"]

MANIFEST = "manifest.json"
_FORMAT_VERSION = 1
_STEP_RE = re.compile(r"^step_(\d{8,})$")


class CheckpointCorrupt(RuntimeError):
    pass


def _dtype_of(name: str):
    """Resolve a dtype name, including jax's ml_dtypes (bfloat16 etc.)."""
    try:
        return np.dtype(name)
    except TypeError:
        import jax.numpy as jnp

        return np.dtype(getattr(jnp, name))


def _to_numpy(leaf):
    from ..core.tensor import Tensor

    if isinstance(leaf, Tensor):
        leaf = leaf._value
    return np.asarray(leaf)


def _is_array_leaf(obj) -> bool:
    from ..core.tensor import Tensor

    if isinstance(obj, (Tensor, np.ndarray)):
        return True
    return hasattr(obj, "shape") and hasattr(obj, "dtype") \
        and not isinstance(obj, (dict, list, tuple))


def _encode(obj, leaves: List[np.ndarray]):
    """State pytree -> JSON skeleton + ordered array leaves."""
    if _is_array_leaf(obj):
        leaves.append(_to_numpy(obj))
        return {"k": "a", "i": len(leaves) - 1}
    if isinstance(obj, dict):
        return {"k": "d", "v": {str(k): _encode(v, leaves)
                                for k, v in obj.items()}}
    if isinstance(obj, tuple):
        return {"k": "t", "v": [_encode(v, leaves) for v in obj]}
    if isinstance(obj, list):
        return {"k": "l", "v": [_encode(v, leaves) for v in obj]}
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return {"k": "p", "v": obj}
    raise TypeError(f"checkpoint state has unsupported leaf type "
                    f"{type(obj).__name__}")


def _decode(skel, leaves: List[Any]):
    kind = skel["k"]
    if kind == "a":
        return leaves[skel["i"]]
    if kind == "d":
        return {k: _decode(v, leaves) for k, v in skel["v"].items()}
    if kind == "t":
        return tuple(_decode(v, leaves) for v in skel["v"])
    if kind == "l":
        return [_decode(v, leaves) for v in skel["v"]]
    if kind == "p":
        return skel["v"]
    raise CheckpointCorrupt(f"unknown skeleton kind {kind!r}")


def _fsync_file(f):
    f.flush()
    os.fsync(f.fileno())


def _fsync_dir(path: str):
    """Durable rename needs the parent directory synced too (best-effort on
    filesystems without O_DIRECTORY support)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # pragma: no cover
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover
        pass
    finally:
        os.close(fd)


class RestoredCheckpoint:
    """restore_latest() result: committed step, state pytree, user meta."""

    def __init__(self, step: int, state: Any, meta: Dict, path: str):
        self.step = step
        self.state = state
        self.meta = meta
        self.path = path

    def __repr__(self):  # pragma: no cover
        return f"RestoredCheckpoint(step={self.step}, path={self.path!r})"


class CheckpointManager:
    """Crash-consistent save/restore over a checkpoint root directory.

    Args:
        root: directory holding all `step_*` checkpoints.
        keep_last_n: committed checkpoints retained by GC (the newest valid
            checkpoint is NEVER removed regardless of this value).
        backend: "npy" (self-contained raw-array files + crc32 checksums) or
            "orbax" (sharded multi-host payload via distributed/checkpoint.py,
            wrapped in this manager's commit protocol).
        store / rank / world_size: process-group KV store (distributed/env
            get_store()) enabling the synchronized multi-host commit: every
            rank reports ready for `step`, rank 0 performs the atomic rename
            only once all ranks have, then publishes the committed marker the
            followers wait on. With replicated params the followers write no
            payload of their own — their save() IS the barrier — so no rank
            can observe (or GC against) a checkpoint some other rank hasn't
            finished with. Single-process default (world_size=1) bypasses
            all of it.
        sync_timeout_s: barrier wait bound; a rank missing past it raises
            rather than committing a checkpoint the cluster disagrees on.
    """

    def __init__(self, root: str, keep_last_n: int = 3, backend: str = "npy",
                 async_save: bool = False, store=None, rank: int = 0,
                 world_size: int = 1, sync_timeout_s: float = 60.0,
                 commit_namespace: str = ""):
        if backend not in ("npy", "orbax", "sharded"):
            raise ValueError(f"unknown checkpoint backend {backend!r}")
        self.root = os.path.abspath(root)
        self.keep_last_n = max(int(keep_last_n), 1)
        self.backend = backend
        # namespace mixed into every commit-coordination store key: the
        # elastic trainer passes the membership generation, so ready
        # counters / nonces left by a save that died mid-commit in an OLD
        # generation can never satisfy (or poison) the reformed world's
        # barrier for the same step number
        self.commit_namespace = str(commit_namespace)
        # async: the host snapshot is taken on the caller thread (so donated
        # device buffers are never read after the step that invalidates
        # them), then file writes + the commit rename happen on a background
        # thread. wait() — called implicitly by the next save() — joins it
        # and re-raises any write error. Commit order is preserved: at most
        # one save is in flight.
        self.async_save = bool(async_save)
        self.store = store
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.sync_timeout_s = float(sync_timeout_s)
        self._thread: Optional[Any] = None
        self._error: Optional[BaseException] = None
        self.last_scan_report: List[Tuple[str, str]] = []  # (path, reason)
        os.makedirs(self.root, exist_ok=True)

    # -- naming ------------------------------------------------------------
    def _dir_for(self, step: int) -> str:
        return os.path.join(self.root, f"step_{int(step):08d}")

    def all_steps(self) -> List[int]:
        """Committed step numbers, ascending (validity not yet checked)."""
        steps = []
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:  # pragma: no cover
            return []
        for name in names:
            m = _STEP_RE.match(name)
            if m and os.path.isdir(os.path.join(self.root, name)):
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # -- save --------------------------------------------------------------
    def save(self, step: int, state: Any, meta: Optional[Dict] = None,
             asynchronous: Optional[bool] = None):
        """Write checkpoint for `step`; commit atomically; GC old ones.

        Any crash (or injected fault) before the commit rename leaves the
        previous checkpoints untouched; a crash after it at worst skips GC.

        With `asynchronous` (default: the manager's `async_save`), the state
        is snapshotted to host memory before returning and the write+commit
        runs on a background thread; call wait() (or just the next save(),
        which implies it) to block until the commit and surface any error.
        The orbax backend always writes synchronously (its payload writer
        reads live device shards).
        """
        if asynchronous is None:
            asynchronous = self.async_save
        self.wait()  # one in-flight save at a time; ordered commits
        if self._sync_enabled and self.rank != 0:
            if self.backend == "sharded":
                return self._follower_write_shard(step, state)
            return self._follower_commit(step)
        if self.backend in ("orbax", "sharded") or not asynchronous:
            return self._save_now(step, state, meta)
        leaves: List[np.ndarray] = []
        skeleton = _encode(state, leaves)  # device->host copies happen HERE
        # host numpy leaves may alias caller arrays mutated by later steps —
        # copy them; _encode already copied device arrays to fresh host
        # buffers via np.asarray
        leaves = [np.array(a, copy=True) for a in leaves]
        meta = json.loads(json.dumps(meta or {}))  # freeze user meta too
        self._error = None

        def _worker():
            try:
                self._write_npy(step, skeleton, leaves, meta)
            except BaseException as e:  # surfaced at wait()/next save()
                self._error = e

        import threading

        self._thread = threading.Thread(
            target=_worker, name="ckpt-save", daemon=True)
        self._thread.start()
        return self._dir_for(step)

    # -- synchronized multi-host commit -------------------------------------
    @property
    def _sync_enabled(self) -> bool:
        return self.store is not None and self.world_size > 1

    def _ckpt_key(self, step: int) -> str:
        ns = f"/{self.commit_namespace}" if self.commit_namespace else ""
        return f"{_CKPT_KEY_PREFIX}{ns}/{int(step)}"

    def _follower_write_shard(self, step: int, state: Any) -> str:
        """Sharded backend, non-leader rank: wait for the leader's nonce
        (it creates the tmp dir before publishing), durably write THIS
        rank's shard into it, then join the ready/committed handshake."""
        from ..distributed import checkpoint as _dck

        key = self._ckpt_key(step)
        nonce = _store_get(self.store, key + "/nonce", self.sync_timeout_s)
        if nonce is None:
            raise TimeoutError(
                f"rank {self.rank}: leader never published a shard nonce "
                f"for step {step} within {self.sync_timeout_s}s")
        nonce = nonce.decode() if isinstance(nonce, bytes) else str(nonce)
        payload = os.path.join(self._dir_for(step) + ".tmp", "shards")
        _dck.write_rank_shard(payload, self.rank, self.world_size, state,
                              nonce)
        return self._follower_commit(step)

    def _follower_commit(self, step: int) -> str:
        """Non-leader rank's save(): report ready, wait for rank 0's commit
        marker. Returns the committed path rank 0 published."""
        key = self._ckpt_key(step)
        with _span("cluster.ckpt_commit", cat="cluster",
                   args={"step": int(step), "role": "follower"}):
            self.store.set(f"{key}/ready_r{self.rank}", b"1")
            self.store.add(key + "/ready", 1)
            try:
                committed = _store_get(self.store, key + "/committed",
                                       self.sync_timeout_s)
            except TimeoutError:
                committed = None
        if committed is None:
            # name who never reported ready — that's where the commit died
            missing = []
            try:
                for r in range(self.world_size):
                    if self.store.get(f"{key}/ready_r{r}",
                                      blocking=False) is None:
                        missing.append(r)
            except TypeError:  # native store: no non-blocking get
                missing = None
            detail = (f"; ranks that never reported ready: {missing}"
                      if missing else
                      f"; every rank reported ready but rank 0 never "
                      f"published the commit marker — it likely died "
                      f"between the barrier and the rename"
                      if missing == [] else "")
            raise TimeoutError(
                f"rank {self.rank}: no committed marker for step {step} "
                f"(key {key + '/committed'!r}) within "
                f"{self.sync_timeout_s}s{detail}")
        _SYNC_COMMITS.inc(role="follower")
        return committed.decode() if isinstance(committed, bytes) \
            else str(committed)

    def _leader_barrier(self, step: int) -> None:
        """Rank 0, immediately before the commit rename: wait until every
        rank (self included) has reported ready for `step`. A timeout
        names the ranks whose ready marker never appeared."""
        key = self._ckpt_key(step)
        self.store.set(f"{key}/ready_r{self.rank}", b"1")
        self.store.add(key + "/ready", 1)
        try:
            got = _store_wait_ge(self.store, key + "/ready",
                                 self.world_size, self.sync_timeout_s)
        except TimeoutError:
            missing = []
            for r in range(self.world_size):
                try:
                    arrived = self.store.get(f"{key}/ready_r{r}",
                                             blocking=False)
                except TypeError:  # native store: no non-blocking get
                    missing = None
                    break
                if arrived is None:
                    missing.append(r)
            raise TimeoutError(
                f"ckpt commit barrier for step {step}: not all "
                f"{self.world_size} ranks ready after "
                f"{self.sync_timeout_s}s"
                + (f"; ranks that never reported ready: {missing}"
                   if missing else "")) from None
        if got < self.world_size:  # pragma: no cover — wait_ge guarantees ge
            raise TimeoutError(
                f"ckpt commit barrier for step {step}: only {got}/"
                f"{self.world_size} ranks ready")

    def _leader_publish(self, step: int, final: str) -> None:
        """Rank 0, after the rename landed: release the followers."""
        self.store.set(self._ckpt_key(step) + "/committed", final)
        _SYNC_COMMITS.inc(role="leader")

    def wait(self):
        """Block until the in-flight async save (if any) commits; re-raise
        its error. Idempotent; no-op when nothing is pending."""
        t = self._thread
        if t is None:
            return
        t.join()
        self._thread = None
        if self._error is not None:
            e, self._error = self._error, None
            raise e

    def _save_now(self, step: int, state: Any, meta: Optional[Dict]):
        if self.backend == "orbax":
            return self._write_orbax(step, state, meta)
        if self.backend == "sharded":
            return self._write_sharded(step, state, meta)
        leaves: List[np.ndarray] = []
        skeleton = _encode(state, leaves)
        return self._write_npy(step, skeleton, leaves, meta)

    def _write_sharded(self, step: int, state: Any, meta: Optional[Dict]):
        """Rank-sharded payload (distributed/checkpoint.write_rank_shard),
        leader side (or the whole job at world 1). Order matters: the tmp
        dir exists and the per-save nonce is published BEFORE the
        followers are released to write their shards into it, every shard
        is durable before the ready barrier passes, and only then does the
        commit rename land."""
        import uuid

        from ..distributed import checkpoint as _dck

        final = self._dir_for(step)
        tmp = final + ".tmp"
        if os.path.exists(tmp):  # stale debris from a previous crash
            shutil.rmtree(tmp)
        payload = os.path.join(tmp, "shards")
        os.makedirs(payload)
        chaos.crash_point("ckpt.begin")
        nonce = uuid.uuid4().hex
        if self._sync_enabled:
            self.store.set(self._ckpt_key(step) + "/nonce", nonce)
        index = _dck.write_rank_shard(payload, 0, self.world_size, state,
                                      nonce)
        _dck.write_shard_index(payload, index)
        chaos.crash_point("ckpt.array")
        return self._finalize(step, tmp, final, skeleton=None, arrays=[],
                              meta=meta)

    def _write_orbax(self, step: int, state: Any, meta: Optional[Dict]):
        final = self._dir_for(step)
        tmp = final + ".tmp"
        if os.path.exists(tmp):  # stale debris from a previous crash
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        chaos.crash_point("ckpt.begin")
        from ..distributed.checkpoint import save_sharded

        save_sharded(state, os.path.join(tmp, "arrays"), async_save=False)
        chaos.crash_point("ckpt.array")
        return self._finalize(step, tmp, final, skeleton=None, arrays=[],
                              meta=meta)

    def _write_npy(self, step: int, skeleton, leaves: List[np.ndarray],
                   meta: Optional[Dict]):
        final = self._dir_for(step)
        tmp = final + ".tmp"
        if os.path.exists(tmp):  # stale debris from a previous crash
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        chaos.crash_point("ckpt.begin")
        arrays = []
        with _span("ckpt.write", cat="io", args={"step": int(step)}):
            for i, arr in enumerate(leaves):
                fname = f"arr_{i}.bin"
                buf = arr.tobytes()
                with open(os.path.join(tmp, fname), "wb") as f:
                    f.write(buf)
                    _fsync_file(f)
                arrays.append({
                    "file": fname,
                    "shape": list(arr.shape),
                    "dtype": arr.dtype.name,
                    "crc32": zlib.crc32(buf) & 0xFFFFFFFF,
                })
                chaos.crash_point("ckpt.array")
        return self._finalize(step, tmp, final, skeleton, arrays, meta)

    def _finalize(self, step: int, tmp: str, final: str, skeleton, arrays,
                  meta: Optional[Dict]):
        try:
            out = self._finalize_inner(step, tmp, final, skeleton, arrays,
                                       meta)
        except BaseException:
            _SAVES.inc(outcome="failed")
            raise
        _SAVES.inc(outcome="committed")
        return out

    def _finalize_inner(self, step: int, tmp: str, final: str, skeleton,
                        arrays, meta: Optional[Dict]):
        chaos.crash_point("ckpt.before_manifest")
        manifest = {
            "version": _FORMAT_VERSION,
            "step": int(step),
            "backend": self.backend,
            "meta": meta or {},
            "skeleton": skeleton,
            "arrays": arrays,
        }
        mpath = os.path.join(tmp, MANIFEST)
        with open(mpath + ".tmp", "w") as f:
            json.dump(manifest, f)
            _fsync_file(f)
        os.replace(mpath + ".tmp", mpath)
        _fsync_dir(tmp)

        chaos.crash_point("ckpt.before_commit")
        if self._sync_enabled:
            with _span("cluster.ckpt_commit", cat="cluster",
                       args={"step": int(step), "role": "leader"}):
                self._leader_barrier(step)
                self._commit_rename(step, tmp, final)
                self._leader_publish(step, final)
        else:
            self._commit_rename(step, tmp, final)

        chaos.crash_point("ckpt.before_gc")
        self._gc()
        return final

    def _commit_rename(self, step: int, tmp: str, final: str) -> None:
        with _span("ckpt.commit", cat="io", args={"step": int(step)}):
            if os.path.exists(final):  # same-step re-save: replace atomically
                old = final + ".replaced"
                if os.path.exists(old):
                    shutil.rmtree(old)
                os.rename(final, old)
                os.rename(tmp, final)
                shutil.rmtree(old)
            else:
                os.rename(tmp, final)  # <- the commit point
            _fsync_dir(self.root)

    # -- GC ----------------------------------------------------------------
    def _gc(self):
        """Delete committed checkpoints beyond keep_last_n (oldest first) and
        any stale `.tmp` debris. The newest VALID checkpoint is never deleted:
        keepers are counted from validated directories, so a corrupt newest
        cannot shadow the good one into deletion."""
        for name in os.listdir(self.root):
            full = os.path.join(self.root, name)
            if name.endswith((".tmp", ".replaced")) and os.path.isdir(full):
                shutil.rmtree(full, ignore_errors=True)
        steps = self.all_steps()
        valid_kept = 0
        keep: set = set()
        for s in reversed(steps):  # newest first
            if valid_kept < self.keep_last_n \
                    and self.validate(self._dir_for(s)) is None:
                keep.add(s)
                valid_kept += 1
        if valid_kept == 0:
            return  # nothing provably good — delete nothing
        for s in steps:
            if s not in keep:
                shutil.rmtree(self._dir_for(s), ignore_errors=True)

    # -- validation / restore ---------------------------------------------
    def validate(self, path: str) -> Optional[str]:
        """None if `path` is a complete, checksum-valid checkpoint; otherwise
        a human-readable corruption reason."""
        mpath = os.path.join(path, MANIFEST)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except FileNotFoundError:
            return "missing manifest"
        except (OSError, json.JSONDecodeError) as e:
            return f"unreadable manifest: {e}"
        if manifest.get("version") != _FORMAT_VERSION:
            return f"unsupported version {manifest.get('version')!r}"
        if manifest.get("backend") == "orbax":
            if not os.path.isdir(os.path.join(path, "arrays")):
                return "missing orbax payload"
            return None  # orbax validates its own array metadata on load
        if manifest.get("backend") == "sharded":
            from ..distributed.checkpoint import validate_rank_sharded

            return validate_rank_sharded(os.path.join(path, "shards"))
        for entry in manifest.get("arrays", ()):
            fpath = os.path.join(path, entry["file"])
            try:
                with open(fpath, "rb") as f:
                    buf = f.read()
            except OSError:
                return f"missing array file {entry['file']}"
            if (zlib.crc32(buf) & 0xFFFFFFFF) != entry["crc32"]:
                return f"checksum mismatch in {entry['file']}"
        return None

    def _load(self, path: str, template: Optional[Any],
              target_world_size: Optional[int] = None,
              target_rank: Optional[int] = None) -> Tuple[Any, Dict]:
        with open(os.path.join(path, MANIFEST)) as f:
            manifest = json.load(f)
        if manifest.get("backend") == "orbax":
            from ..distributed.checkpoint import load_sharded

            state = load_sharded(os.path.join(path, "arrays"),
                                 template=template)
            return state, manifest.get("meta", {})
        if manifest.get("backend") == "sharded":
            from ..distributed.checkpoint import load_sharded

            # default to THIS manager's topology: rank r of W reads back
            # exactly its slice; the elastic trainer passes
            # target_world_size=1 to gather the full state for a reform
            tws = self.world_size if target_world_size is None \
                else int(target_world_size)
            tr = self.rank if target_rank is None else int(target_rank)
            state = load_sharded(os.path.join(path, "shards"),
                                 template=template,
                                 target_world_size=tws,
                                 target_rank=min(tr, tws - 1))
            return state, manifest.get("meta", {})
        leaves = []
        for entry in manifest["arrays"]:
            with open(os.path.join(path, entry["file"]), "rb") as f:
                buf = f.read()
            arr = np.frombuffer(buf, dtype=_dtype_of(entry["dtype"]))
            leaves.append(arr.reshape(entry["shape"]))
        state = _decode(manifest["skeleton"], leaves)
        if template is not None:
            state = _place_like(state, template)
        return state, manifest.get("meta", {})

    def restore_latest(self, template: Optional[Any] = None, *,
                       target_world_size: Optional[int] = None,
                       target_rank: Optional[int] = None
                       ) -> Optional[RestoredCheckpoint]:
        """Newest valid checkpoint (validating manifest + checksums), falling
        back to older ones on corruption; None when nothing valid exists.
        `template` (a pytree of Tensors/arrays matching the saved structure)
        places restored arrays onto the template leaves' shardings.

        For the "sharded" backend, `target_world_size=`/`target_rank=`
        reshard on load across a different rank count (default: this
        manager's own rank/world — each rank reads back its slice);
        `target_world_size=1` gathers the full state, which is how the
        elastic trainer re-seeds a reformed, smaller world."""
        self.wait()  # a just-issued async save must be visible (or raise)
        self.last_scan_report = []
        for step in reversed(self.all_steps()):
            path = self._dir_for(step)
            reason = self.validate(path)
            if reason is not None:
                self.last_scan_report.append((path, reason))
                continue
            try:
                state, meta = self._load(path, template,
                                         target_world_size, target_rank)
            except Exception as e:  # torn beyond what validate caught
                self.last_scan_report.append((path, f"load failed: {e}"))
                continue
            return RestoredCheckpoint(step, state, meta, path)
        return None


def _place_like(state, template):
    """Pair restored numpy leaves with template leaves; device_put onto the
    template's sharding when it has one (mesh-reshard on load, same contract
    as distributed/checkpoint.load_sharded)."""
    import jax

    from ..core.tensor import Tensor

    if _is_array_leaf(template):
        t = template._value if isinstance(template, Tensor) else template
        arr = np.asarray(state)
        sharding = getattr(t, "sharding", None)
        if sharding is not None:
            return jax.device_put(arr, sharding)
        return jax.numpy.asarray(arr)
    if isinstance(template, dict):
        return {k: _place_like(state[k], template[k]) for k in template}
    if isinstance(template, (list, tuple)):
        out = [_place_like(s, t) for s, t in zip(state, template)]
        return tuple(out) if isinstance(template, tuple) else out
    return state
