"""Elastic data-parallel training: survive rank loss by reforming the mesh.

`ElasticTrainer` runs true data parallelism over the process-group store
(distributed/elastic.py): every member computes gradients on its slice of
the global batch, publishes them, and applies the batch-size-weighted
average — so the parameter trajectory is a deterministic function of the
GLOBAL batch, independent of how many members split it. That invariance is
what makes the elastic guarantees testable: after a rank dies, the
survivors reform at N−1 and the loss trajectory must continue within
floating-point reassociation noise of the no-failure run.

The loop per global step:

    1. chaos check — an armed rank-kill stops heartbeating and exits
       (an unannounced crash as far as the survivors can tell);
    2. membership poll — adopt/propose a new generation view if leases
       expired, someone left, or a joiner announced itself;
    3. shard the global batch by the rebalancer's shares (equal split
       unless the r10 straggler signal shifted them within the bounded
       skew), fwd+bwd on this member's shard (jitted);
    4. store allreduce: publish grads + {shard size, loss, wall time},
       collect every member's, weighted-average in sorted member order
       (identical floats on every member — params stay bitwise-replicated);
    5. a collection timeout names the missing members (PeerLostError):
       wait for their leases to expire, adopt the reformed view, and
       REFORM — rebuild the CheckpointManager for the new rank/world,
       invalidate the jitted executables traced for the old world size,
       restore the full state from the last committed rank-sharded
       checkpoint (load_sharded target_world_size=1), and resume from
       its step;
    6. every `save_every` steps, a synchronized rank-sharded checkpoint
       (CheckpointManager backend="sharded", commit keys namespaced by
       the membership generation so a failed pre-reform save can never
       satisfy the reformed world's barrier).

Step 0 always commits a checkpoint (the initial rendezvous), so "the last
committed sharded checkpoint" exists from the first possible failure on.

Buffers (e.g. BN stats) are carried per-member, not averaged — models with
running statistics will diverge across members; the elastic path targets
buffer-free (or frozen-buffer) training. Gradient clipping is not applied
on this path.

Threads-as-ranks (tests, tools/faultbench.py `elastic`): N threads share
one InProcStore, each owning its own model/optimizer/trainer. The same
code runs one-process-per-rank over a native TCPStore.
"""
from __future__ import annotations

import statistics
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import chaos
from .checkpoint_manager import CheckpointManager
from ..core.flags import define_flag, get_flag
from ..distributed.checkpoint import split_bounds
from ..distributed.elastic import (ElasticMembership, MembershipView,
                                   PeerLostError, StoreReducer)
from ..observability import cluster as _cluster  # noqa: F401 — straggler flags
from ..observability import flight_recorder as _flight
from ..observability.registry import counter as _counter

define_flag("elastic_rebalance_skew", 0.0,
            "Bound on straggler-aware micro-batch rebalancing: a detected "
            "straggler's batch share can shrink to at most (1 - skew) of "
            "its equal share, the slack spread over the others. 0 disables "
            "rebalancing (always equal split).")
define_flag("elastic_eject_patience", 0,
            "Auto-eject chronically slow ranks: when the rebalancer has "
            "pinned a member at the (1 - skew) share clamp for this many "
            "consecutive observation windows, the lowest-id non-straggler "
            "member ejects it from the view and training reforms at N-1 "
            "(membership_ejections_total counts it; the flight recorder "
            "dumps the evidence). 0 (default) disables auto-ejection — "
            "eject() stays a manual operation.")

_REBALANCES = _counter("elastic_rebalance_events_total",
                       "Steps whose batch shares deviated from the equal "
                       "split due to the straggler signal.", always=True)
_REFORM_STEPS = _counter("elastic_reforms_total",
                         "Mesh reformations performed by ElasticTrainer.",
                         always=True)
_EJECTIONS = _counter("membership_ejections_total",
                      "Members auto-ejected by ElasticTrainer for chronic "
                      "straggling pinned past the rebalance clamp.",
                      always=True)

__all__ = ["ElasticTrainer", "MicroBatchRebalancer"]


class MicroBatchRebalancer:
    """Deterministic straggler-aware batch-share policy, short of ejection.

    Fed the per-member wall times every member saw in the SAME allreduce
    records, so every member computes identical shares — replication of
    the parameter state never depends on who computed what. Straggler
    detection reuses the r10 thresholds: a member whose smoothed wall time
    exceeds `FLAGS_straggler_k` x median for `FLAGS_straggler_m`
    consecutive steps gets its share scaled by median/ema, floored at
    (1 - skew) of equal. The weighted gradient average keeps the update
    math exact under ANY share split, so rebalancing never perturbs the
    loss trajectory — only who computes how much of it."""

    def __init__(self, *, skew: Optional[float] = None,
                 k: Optional[float] = None, m: Optional[int] = None,
                 ema_alpha: float = 0.5):
        self.skew = float(skew if skew is not None
                          else get_flag("elastic_rebalance_skew"))
        self.k = float(k if k is not None else get_flag("straggler_k"))
        self.m = int(m if m is not None else get_flag("straggler_m"))
        self.ema_alpha = float(ema_alpha)
        self._ema: Dict[int, float] = {}
        self._streak: Dict[int, int] = {}
        self._pinned: Dict[int, int] = {}
        self.weights: Dict[int, float] = {}

    def reset(self) -> None:
        self._ema.clear()
        self._streak.clear()
        self._pinned.clear()
        self.weights.clear()

    def pinned_streak(self, member: int) -> int:
        """Consecutive observation windows this member's weight sat AT
        the (1 - skew) clamp — i.e. it is slower than the rebalance bound
        can compensate for. Deterministic across members (same walls in,
        same streak out), so the auto-eject decision built on it needs no
        extra coordination."""
        return self._pinned.get(member, 0)

    def observe(self, step: int, walls: Dict[int, float]) -> None:
        """Fold one step's per-member wall times (from the allreduce
        metadata — identical on every member) into the straggler state.

        Each member is judged against the median of the OTHERS (including
        itself would make k=2 detection impossible at world 2, where the
        straggler drags the median to the midpoint). The streak counts
        consecutive slow RAW walls — one fast step resets it — while the
        weight magnitude uses the smoothed EMA ratio."""
        a = self.ema_alpha
        for m in list(self._ema):
            if m not in walls:  # member reformed away
                self._ema.pop(m, None)
                self._streak.pop(m, None)
                self._pinned.pop(m, None)
                self.weights.pop(m, None)
        for m, w in walls.items():
            prev = self._ema.get(m)
            self._ema[m] = float(w) if prev is None \
                else a * float(w) + (1 - a) * prev
        self.weights = {}
        for m in sorted(walls):
            others_w = [float(walls[o]) for o in walls if o != m]
            base_w = statistics.median(others_w) if others_w else 0.0
            if base_w > 0 and float(walls[m]) > self.k * base_w:
                self._streak[m] = self._streak.get(m, 0) + 1
            else:
                self._streak[m] = 0
            if self.skew > 0 and self._streak[m] >= self.m:
                others_e = [self._ema[o] for o in walls if o != m]
                base_e = statistics.median(others_e) if others_e else 0.0
                ema = self._ema[m]
                ratio = base_e / ema if ema > 0 else 1.0
                self.weights[m] = max(1.0 - self.skew, ratio)
                if ratio <= 1.0 - self.skew:
                    self._pinned[m] = self._pinned.get(m, 0) + 1
                else:
                    self._pinned[m] = 0
            else:
                self.weights[m] = 1.0
                self._pinned[m] = 0

    def shares(self, batch_size: int, members: Sequence[int]) -> List[int]:
        """Per-member item counts summing to batch_size, in member order.
        Equal split (split_bounds — matches the checkpoint slicing rule)
        unless a straggler weight is active; then largest-remainder
        apportionment of the weighted shares, every member keeping at
        least one item."""
        B, n = int(batch_size), len(members)
        if B < n:
            raise ValueError(f"global batch of {B} cannot feed {n} members")
        w = [self.weights.get(m, 1.0) for m in members]
        if self.skew <= 0 or all(abs(x - 1.0) < 1e-12 for x in w):
            return [b - a for a, b in split_bounds(B, n)]
        _REBALANCES.inc()
        total_w = sum(w)
        raw = [B * x / total_w for x in w]
        out = [max(1, int(r)) for r in raw]
        # largest-remainder correction to land exactly on B, deterministic
        # tie-break by position
        while sum(out) > B:
            i = max(range(n), key=lambda j: (out[j] - raw[j], j))
            if out[i] <= 1:
                break
            out[i] -= 1
        while sum(out) < B:
            i = max(range(n), key=lambda j: (raw[j] - out[j], -j))
            out[i] += 1
        return out


class ElasticTrainer:
    """Data-parallel training loop that survives rank loss via mesh
    reformation and checkpoint resharding (see module docstring).

    Args:
        model / loss_fn / optimizer: as for jit.trainer.TrainStep — every
            member builds its OWN identically-initialized copy.
        root: checkpoint root shared by all members (rank-sharded layout).
        store: the process-group store all members share.
        member_id: this member's id (any ints; dp ranks are their sorted
            order within the current view).
        members: the initial membership.
        save_every: sharded-checkpoint cadence in global steps.
        heartbeat_s / lease_ttl_s: liveness knobs (default: flags).
        allreduce_timeout_s: how long collect() waits before naming the
            missing members (default: a few lease TTLs).
        rebalance_skew: bound for straggler rebalancing (default: flag;
            0 disables).
        eject_patience: consecutive windows a member may sit pinned at
            the rebalance clamp before it is auto-ejected (default:
            FLAGS_elastic_eject_patience; 0 disables).
        clock: injectable monotonic clock for the membership layer.
    """

    def __init__(self, model, loss_fn, optimizer, root: str, *,
                 store, member_id: int, members: Sequence[int],
                 save_every: int = 5, keep_last_n: int = 3,
                 heartbeat_s: Optional[float] = None,
                 lease_ttl_s: Optional[float] = None,
                 allreduce_timeout_s: Optional[float] = None,
                 sync_timeout_s: float = 20.0,
                 rebalance_skew: Optional[float] = None,
                 eject_patience: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        from ..jit.trainer import TrainStep

        self.model = model
        self.optimizer = optimizer
        self.root = str(root)
        self.store = store
        self.member_id = int(member_id)
        self.save_every = int(save_every)
        self.keep_last_n = int(keep_last_n)
        self.sync_timeout_s = float(sync_timeout_s)
        # TrainStep is the state container + pure fwd/bwd provider; its
        # fused executable is not used (the update must see the STORE-
        # averaged grads), donation off for the same UAF reason as
        # ResilientTrainer
        self.step = TrainStep(model, loss_fn, optimizer, donate=False,
                              nan_guard=False, telemetry=False)
        self.membership = ElasticMembership(
            store, member_id, members, lease_ttl_s=lease_ttl_s,
            heartbeat_s=heartbeat_s, clock=clock)
        self.reducer = StoreReducer(store, member_id)
        self.rebalancer = MicroBatchRebalancer(skew=rebalance_skew)
        self.eject_patience = int(
            get_flag("elastic_eject_patience")
            if eject_patience is None else eject_patience)
        self.allreduce_timeout_s = float(
            allreduce_timeout_s if allreduce_timeout_s is not None
            else max(3.0 * self.membership.lease_ttl_s, 2.0))
        self._gstep = 0
        self.losses: Dict[int, float] = {}     # step -> global loss (the
                                               # final value after replays)
        self.step_walls: List[Tuple[int, float, int, int]] = []
        # (step, this member's wall_s, gen, world) — every recorded step
        self.reforms: List[dict] = []
        self.manager = self._make_manager()
        self._build_executables()

    # -- compiled pieces ----------------------------------------------------
    def _build_executables(self) -> None:
        """(Re)build the jitted fwd/bwd and optimizer apply as FRESH
        closures — on reform this drops every trace/executable keyed on
        the old world's shard shapes (jax's caches key on callable
        identity), alongside TrainStep.invalidate_executables() for the
        step program itself."""
        import jax

        fwd = self.step._fwd_bwd_fn
        apply_ = self.optimizer.functional_update

        def fresh_fwd(p_vals, b_vals, batch):
            return fwd(p_vals, b_vals, batch)

        def fresh_apply(p_vals, g_vals, states, lr):
            return apply_(p_vals, g_vals, states, lr)

        self._fwd = jax.jit(fresh_fwd)
        self._apply = jax.jit(fresh_apply)

    # -- checkpoint plumbing ------------------------------------------------
    def _make_manager(self) -> CheckpointManager:
        v = self.membership.view
        return CheckpointManager(
            self.root, keep_last_n=self.keep_last_n, backend="sharded",
            store=self.store if v.world_size > 1 else None,
            rank=v.dp_rank(self.member_id), world_size=v.world_size,
            sync_timeout_s=self.sync_timeout_s,
            commit_namespace=f"g{v.gen}")

    def _state(self) -> Dict[str, Any]:
        return {
            "params": [p._value for p in self.step.params],
            "buffers": [b._value for b in self.step.buffers],
            "opt_state": self.step.opt_state,
        }

    def _meta(self) -> Dict[str, Any]:
        v = self.membership.view
        return {
            "step": int(self._gstep),
            "opt_step_count": int(self.optimizer._step_count),
            "gen": int(v.gen),
            "world_size": int(v.world_size),
            "members": list(v.members),
        }

    def _save(self) -> None:
        self.manager.save(self._gstep, self._state(), meta=self._meta())

    def _restore(self):
        """Gather the FULL state from the newest committed rank-sharded
        checkpoint — regardless of the world size that wrote it — and
        resume from its step. This is the resharding path: load_sharded
        re-slices at target_world_size=1."""
        import jax.numpy as jnp

        restored = self.manager.restore_latest(
            template=self._state(), target_world_size=1, target_rank=0)
        if restored is None:
            return None
        state, meta = restored.state, restored.meta
        for p, v in zip(self.step.params, state["params"]):
            p._value = jnp.asarray(v)
        for b, v in zip(self.step.buffers, state["buffers"]):
            b._value = jnp.asarray(v)
        import jax

        self.step.opt_state = jax.tree_util.tree_map(
            jnp.asarray, state["opt_state"])
        self._gstep = int(meta.get("step", restored.step))
        self.optimizer._step_count = int(
            meta.get("opt_step_count", self._gstep))
        return restored

    # -- reformation --------------------------------------------------------
    def _reform(self, view: MembershipView) -> None:
        """Membership changed: rebuild everything keyed on rank/world —
        checkpoint manager, jitted executables, rebalancer, reducer —
        then re-seed the full state from the last committed checkpoint."""
        _REFORM_STEPS.inc()
        self.manager = self._make_manager()
        self.step.invalidate_executables()
        self._build_executables()
        self.rebalancer.reset()
        self.reducer.reset()
        at_step = self._gstep
        restored = self._restore()
        if restored is None:
            raise RuntimeError(
                f"member {self.member_id}: no committed checkpoint to "
                f"reform from at gen {view.gen} (root {self.root!r}) — "
                f"the initial step-0 save should have guaranteed one")
        self.reforms.append({
            "gen": int(view.gen), "members": list(view.members),
            "world_size": int(view.world_size),
            "detected_at_step": int(at_step),
            "resumed_step": int(self._gstep),
            "dp_rank": self.membership.view.dp_rank(self.member_id),
        })

    def _await_reform(self) -> Optional[MembershipView]:
        """After a PeerLostError (or a failed synchronized save): keep
        polling until the missing members' leases expire and a new view is
        agreed. None if the deadline passes with membership unchanged
        (peers alive but slow — the caller retries the step)."""
        m = self.membership
        deadline = time.monotonic() + m.lease_ttl_s \
            + 4 * m.heartbeat_s + 2.0
        while time.monotonic() < deadline:
            changed = m.poll()
            if changed is not None:
                return changed
            time.sleep(max(m.heartbeat_s / 2, 0.01))
        return None

    # -- one global step ----------------------------------------------------
    @staticmethod
    def _batch_leading_dim(batch) -> int:
        import jax

        leaves = jax.tree_util.tree_leaves(batch)
        if not leaves:
            raise ValueError("empty batch")
        return int(np.asarray(leaves[0]).shape[0])

    @staticmethod
    def _slice_batch(batch, lo: int, hi: int):
        import jax

        return jax.tree_util.tree_map(
            lambda leaf: np.asarray(leaf)[lo:hi], batch)

    def _train_step(self, batch) -> None:
        view = self.membership.view
        members = view.members
        idx = view.dp_rank(self.member_id)
        t0 = time.perf_counter()
        delay = chaos.rank_delay(self.member_id)
        if delay > 0:  # injected straggler
            time.sleep(delay)
        B = self._batch_leading_dim(batch)
        shares = self.rebalancer.shares(B, members)
        lo = sum(shares[:idx])
        hi = lo + shares[idx]
        shard = self._slice_batch(batch, lo, hi)
        param_vals = [p._value for p in self.step.params]
        buffer_vals = [b._value for b in self.step.buffers]
        loss, g_vals, new_buf = self._fwd(param_vals, buffer_vals, shard)
        g_np = [np.asarray(g) for g in g_vals]
        wall = time.perf_counter() - t0
        meta = {"n": int(hi - lo), "loss": float(loss),
                "wall_s": float(wall), "member": self.member_id}
        self.reducer.publish(view.gen, self._gstep, meta, g_np)
        contrib = self.reducer.collect(
            view.gen, self._gstep, members,
            timeout_s=self.allreduce_timeout_s)
        # weighted average in sorted member order: identical float ops on
        # every member, so params stay bitwise-replicated — and the result
        # equals the full-batch gradient no matter how shares were split
        total_n = sum(contrib[m][0]["n"] for m in members)
        g_avg: Optional[List[np.ndarray]] = None
        global_loss = 0.0
        for m in members:
            c_meta, arrs = contrib[m]
            w = c_meta["n"] / total_n
            global_loss += c_meta["loss"] * w
            if g_avg is None:
                g_avg = [a * np.asarray(w, a.dtype) for a in arrs]
            else:
                for i, a in enumerate(arrs):
                    g_avg[i] = g_avg[i] + a * np.asarray(w, a.dtype)
        import jax.numpy as jnp

        lr = self.optimizer.get_lr() if hasattr(self.optimizer, "get_lr") \
            else float(self.optimizer._learning_rate)
        new_p, new_s = self._apply(
            param_vals, [jnp.asarray(g) for g in g_avg],
            self.step.opt_state, lr)
        for p, v in zip(self.step.params, new_p):
            p._value = v
        for b, v in zip(self.step.buffers, new_buf):
            b._value = v
        self.step.opt_state = new_s
        self.optimizer._step_count += 1
        self.rebalancer.observe(
            self._gstep, {m: float(contrib[m][0]["wall_s"])
                          for m in members})
        self.losses[self._gstep] = float(global_loss)
        self.step_walls.append((self._gstep,
                                float(time.perf_counter() - t0),
                                int(view.gen), int(view.world_size)))

    # -- the loop -----------------------------------------------------------
    def run(self, batches: Sequence, *, total_steps: Optional[int] = None,
            resume: bool = True) -> Dict[str, Any]:
        """Train for `total_steps` global steps (default: len(batches)),
        cycling through `batches`. Returns a report dict whose "status" is
        "completed", "killed" (this member died to an armed chaos kill),
        or "ejected" (reformed out of the view). Survivors keep running
        through any number of membership changes."""
        batches = list(batches)
        total = int(total_steps) if total_steps is not None \
            else len(batches)
        me = self.member_id
        report: Dict[str, Any] = {
            "member": me, "status": "completed", "steps_run": 0,
            "retries": 0,
        }
        self.membership.start()
        try:
            restored = self._restore() if resume else None
            if restored is None:
                self._save()  # the step-0 rendezvous: a committed
                              # checkpoint exists before any failure can
            step_retries = 0
            while self._gstep < total:
                if chaos.should_kill_rank(me, self._gstep):
                    chaos.note_rank_killed(me)
                    self.membership.stop()  # heartbeat dies unannounced
                    report["status"] = "killed"
                    report["killed_at_step"] = int(self._gstep)
                    return report
                changed = self.membership.poll()
                if changed is not None:
                    if not changed.contains(me):
                        report["status"] = "ejected"
                        return report
                    self._reform(changed)
                    continue
                try:
                    self._train_step(batches[self._gstep % len(batches)])
                except PeerLostError as e:
                    view = self._await_reform()
                    if view is not None:
                        if not view.contains(me):
                            report["status"] = "ejected"
                            return report
                        self._reform(view)
                        step_retries = 0
                        continue
                    if all(self.membership.is_alive(m) for m in e.missing) \
                            and step_retries < 10:
                        # peers are heartbeating, just slow (compile storm,
                        # loaded host): retry the same step — republishing
                        # the same key is an idempotent overwrite
                        step_retries += 1
                        report["retries"] += 1
                        continue
                    raise
                step_retries = 0
                self._gstep += 1
                report["steps_run"] += 1
                if self._maybe_auto_eject(report):
                    continue            # reformed at N-1 inside
                if self.save_every and self._gstep < total \
                        and self._gstep % self.save_every == 0:
                    if not self._checked_save(report):
                        return report   # ejected while saving
            self._checked_save(report)
            return report
        finally:
            self.membership.stop()
            self._finalize_report(report)

    def _maybe_auto_eject(self, report: Dict[str, Any]) -> bool:
        """Flag-gated auto-ejection of a chronically slow member: once
        the rebalancer has pinned someone at the (1 - skew) clamp for
        `eject_patience` consecutive windows, rebalancing has hit its
        bound and the straggler is still throttling every step — remove
        it. The pinned streak is computed from allreduce metadata that is
        identical on every member, so all survivors agree on the victim;
        the lowest-id non-straggler acts (eject is an idempotent store
        tombstone — a racing duplicate would be harmless, but a single
        deterministic actor keeps the counters honest) and everyone else
        adopts the new view through their own poll(). Returns True when
        THIS member ejected someone and reformed."""
        patience = self.eject_patience
        if patience <= 0:
            return False
        view = self.membership.view
        if view.world_size <= 1:
            return False
        me = self.member_id
        victims = [m for m in view.members
                   if self.rebalancer.pinned_streak(m) >= patience]
        victims = [m for m in victims if m != me]
        if not victims:
            return False
        actor = min(m for m in view.members if m not in victims)
        if me != actor:
            return False                # the actor's tombstone reaches us
        victim = min(victims)           # one per window; streaks persist
        info = {
            "member": int(victim), "by": int(me),
            "step": int(self._gstep), "gen": int(view.gen),
            "pinned_windows": int(self.rebalancer.pinned_streak(victim)),
            "weight": float(self.rebalancer.weights.get(victim, 1.0)),
        }
        _EJECTIONS.inc()
        _flight.on_member_ejected(info)
        report.setdefault("ejections", []).append(info)
        new_view = self.membership.eject(victim)
        if new_view is not None and new_view.contains(me):
            self._reform(new_view)
            return True
        return False

    def _checked_save(self, report: Dict[str, Any]) -> bool:
        """A synchronized save can be the first place a death is noticed
        (the barrier times out instead of the allreduce): treat that like
        a peer loss — reform and carry on; the failed attempt never
        committed, and its coordination keys are namespaced to the dead
        generation. It can equally be where THIS member first learns it
        was ejected (the others reformed to a new generation mid-save and
        will never join the old one's commit) — then the report flips to
        "ejected" and False comes back so the loop exits cleanly."""
        try:
            self._save()
        except TimeoutError:
            view = self._await_reform()
            if view is None:
                raise
            if not view.contains(self.member_id):
                report["status"] = "ejected"
                return False
            self._reform(view)
        return True

    def _finalize_report(self, report: Dict[str, Any]) -> None:
        v = self.membership.view
        report["step"] = int(self._gstep)
        report["final_gen"] = int(v.gen)
        report["final_world_size"] = int(v.world_size)
        report["final_members"] = list(v.members)
        report["reforms"] = list(self.reforms)
        report["losses"] = {int(k): float(self.losses[k])
                            for k in sorted(self.losses)}
        report["step_walls"] = [list(t) for t in self.step_walls]
        if report.get("status") == "completed" and self.step.params:
            # settle + rematerialize (same donation-UAF hygiene as
            # ResilientTrainer._finish, though donation is off here)
            import jax
            import jax.numpy as jnp

            for p in self.step.params:
                p._value = jnp.array(jax.block_until_ready(p._value))
