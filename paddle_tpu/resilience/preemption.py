"""Preemption-aware shutdown.

Production TPU pools preempt VMs with a SIGTERM grace window (maintenance
events, spot reclaims — see PAPERS.md on preemptible TPU fleets); the
reference reacts through its elastic manager's membership watch. Here both
signals land in one PreemptionHandler: POSIX signals set a flag the training
loop polls between steps (never mid-XLA-dispatch), and an elastic-manager
hook maps "membership shrank" onto the same flag, so the ResilientTrainer
has exactly one preemption source to honor with a final synchronized
checkpoint + clean exit.
"""
from __future__ import annotations

import signal as _signal
import threading
from typing import Callable, List, Optional, Tuple

__all__ = ["PreemptionHandler"]


class PreemptionHandler:
    """Latches SIGTERM/SIGINT (and elastic membership loss) into a flag.

    Usage:
        handler = PreemptionHandler()
        with handler:                       # installs signal handlers
            trainer.run(..., preemption=handler)

    Signal handlers only install from the main thread (CPython rule); from
    other threads install() degrades to manual trigger()-only mode.
    """

    def __init__(self, signals: Tuple[int, ...] = (_signal.SIGTERM,
                                                   _signal.SIGINT)):
        self.signals = tuple(signals)
        self._event = threading.Event()
        self._prev: List[Tuple[int, object]] = []
        self._installed = False
        self.reason: Optional[str] = None
        self.count = 0
        self._callbacks: List[Callable[[str], None]] = []

    # -- flag --------------------------------------------------------------
    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def trigger(self, reason: str = "manual"):
        """Latch preemption programmatically (elastic hook, chaos harness)."""
        self.count += 1
        first = not self._event.is_set()
        if first:
            self.reason = reason
            self._event.set()
            # flight-recorder forensics on the FIRST latch only (repeat
            # signals in the grace window must not spam dumps); lazy import
            # keeps signal-handler context cheap, and observability failures
            # must never break the shutdown path
            try:
                from ..observability import flight_recorder as _flight

                _flight.on_preemption(reason)
            except Exception:
                pass
        for cb in self._callbacks:
            try:
                cb(reason)
            except Exception:  # noqa: BLE001 — callbacks must not kill the handler
                pass

    def reset(self):
        self._event.clear()
        self.reason = None

    def add_callback(self, cb: Callable[[str], None]):
        self._callbacks.append(cb)

    # -- signals -----------------------------------------------------------
    def _on_signal(self, signum, frame):  # noqa: ARG002
        self.trigger(f"signal:{_signal.Signals(signum).name}")

    def install(self):
        if self._installed:
            return self
        try:
            for sig in self.signals:
                prev = _signal.signal(sig, self._on_signal)
                self._prev.append((sig, prev))
            self._installed = True
        except ValueError:  # not in main thread: trigger()-only mode
            for sig, prev in self._prev:
                _signal.signal(sig, prev)
            self._prev.clear()
        return self

    def uninstall(self):
        if not self._installed:
            return
        for sig, prev in self._prev:
            try:
                _signal.signal(sig, prev)
            except (ValueError, OSError):  # pragma: no cover
                pass
        self._prev.clear()
        self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # -- elastic integration ----------------------------------------------
    def attach_elastic(self, manager, expected_np: int):
        """Watch the ElasticManager's membership: a shrink below expected_np
        (a peer's heartbeat vanished — host loss or TPU maintenance event)
        latches preemption so this rank checkpoints and exits cleanly rather
        than hanging in a collective with a dead peer."""

        def _cb(alive):
            if len(alive) < expected_np and not self.requested:
                self.trigger(f"elastic:{len(alive)}/{expected_np} alive")

        manager.add_watch_callback(_cb)
        return self
