from . import gpt  # noqa: F401
from .gpt import GPTConfig, GPTForCausalLM, GPTModel  # noqa: F401
