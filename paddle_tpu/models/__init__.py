from . import gpt  # noqa: F401
from .gpt import GPTConfig, GPTForCausalLM, GPTModel  # noqa: F401
from . import llama  # noqa: F401
from .llama import LlamaConfig, LlamaForCausalLM, LlamaModel  # noqa: F401
from . import bert  # noqa: F401
from .bert import (  # noqa: F401
    BertConfig,
    BertForPretraining,
    BertForSequenceClassification,
    BertModel,
)
