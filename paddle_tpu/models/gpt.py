"""GPT family — the flagship model (BASELINE configs[3]: GPT-3 1.3B hybrid).

Reference model zoo analog: the fleetx/gpt models used by Fleet hybrid
examples (hybrid_parallel_pp_amp.py payloads, fused_multi_transformer ops in
paddle/fluid/operators/fused/).

TPU-first design decisions:
  * pre-LN transformer, bf16-friendly (fp32 softmax/norm statistics inside
    the kernels);
  * attention lowers to the Pallas flash kernel on TPU (ops/pallas), else the
    jnp reference path;
  * TP is expressed as weight shardings (Column/Row/VocabParallel layers) —
    GSPMD inserts the collectives; the same module runs single-chip unchanged;
  * rotary or learned positions; weight-tied LM head.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from .. import nn
from ..core.tensor import Tensor
from ..distributed.fleet.mp_layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from ..nn import functional as F
from ..ops import api
from .generation import GenerationMixin


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 0  # 0 -> 4*hidden
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    use_rotary: bool = False
    tie_word_embeddings: bool = True
    recompute: bool = False           # activation checkpointing per block
    recompute_policy: str = None      # jax.checkpoint policy name (None=full)
    sequence_parallel: str = None     # None | 'ring' | 'ulysses': attention
                                      # over the 'sep' mesh axis (long context)
    sep_axis: str = "sep"

    def __post_init__(self):
        if not self.intermediate_size:
            self.intermediate_size = 4 * self.hidden_size

    @staticmethod
    def gpt3_1p3b():
        return GPTConfig(hidden_size=2048, num_layers=24, num_heads=16,
                         max_position_embeddings=2048)

    @staticmethod
    def tiny():
        return GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                         num_heads=4, max_position_embeddings=256,
                         hidden_dropout_prob=0.0, attention_dropout_prob=0.0)


class CausalSelfAttention(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        c = config
        self.num_heads = c.num_heads
        self.head_dim = c.hidden_size // c.num_heads
        self.hidden_size = c.hidden_size
        self.qkv_proj = ColumnParallelLinear(c.hidden_size, 3 * c.hidden_size, gather_output=False)
        self.out_proj = RowParallelLinear(c.hidden_size, c.hidden_size, input_is_parallel=True)
        self.attn_dropout_p = c.attention_dropout_prob
        self.resid_dropout = nn.Dropout(c.hidden_dropout_prob)
        self.sequence_parallel = c.sequence_parallel
        self.sep_axis = c.sep_axis
        if c.sequence_parallel and c.sequence_parallel not in ("ring", "ulysses"):
            raise ValueError(
                f"GPTConfig.sequence_parallel must be None, 'ring' or "
                f"'ulysses', got {c.sequence_parallel!r}")
        if c.sequence_parallel and c.attention_dropout_prob:
            raise ValueError(
                "attention dropout is not supported under context "
                "parallelism (the ring/Ulysses kernels are deterministic); "
                "set attention_dropout_prob=0")

    def forward(self, x, rope=None, cache=None, pos=None, segments=None):
        b, s, h = x.shape
        qkv = self.qkv_proj(x)
        qkv = api.reshape(qkv, [b, s, self.num_heads, 3 * self.head_dim])
        q, k, v = api.split(qkv, 3, axis=-1)
        if rope is not None:
            if len(rope) == 3:  # packed: (cos_table, sin_table, pos2d)
                q, k = api.rotary_position_embedding_packed(
                    q, k, rope[0], rope[1], rope[2])
            else:
                q, k = api.rotary_position_embedding(q, k, rope[0], rope[1])
        if cache is not None:
            if self.sequence_parallel:
                raise NotImplementedError(
                    "KV-cache decoding under sequence_parallel is not "
                    "supported; gather the sequence (sequence_parallel=None) "
                    "for generation")
            if hasattr(cache, "block_table"):
                # paged decode (serving engine): one query token per slot,
                # KV scattered across fixed-size blocks; ragged per-slot
                # lengths live in the cache view (ops paged_cached_attention)
                out, new_k, new_v = api.paged_cached_attention(
                    q, k, v, cache.k_pages, cache.v_pages,
                    cache.block_table, cache.seq_lens)
                out = api.reshape(out, [b, s, h])
                return self.resid_dropout(self.out_proj(out)), (new_k, new_v)
            # decode path: static-shape KV ring updated in place, causal
            # masking against the absolute position (models/generation.py)
            out, new_k, new_v = api.cached_multihead_attention(
                q, k, v, cache[0], cache[1], pos)
            out = api.reshape(out, [b, s, h])
            return self.resid_dropout(self.out_proj(out)), (new_k, new_v)
        if segments is not None:
            if self.sequence_parallel:
                raise NotImplementedError(
                    "packed (segments=) batches are not supported under "
                    "sequence_parallel; gather the sequence first")
            # packed-document path: attention restricted to each document
            # (native pack_varlen batches; varlen flash kernel on TPU)
            out = api.segmented_attention(q, k, v, segments, causal=True)
        elif self.sequence_parallel:
            # long-context path: sequence sharded over the 'sep' mesh axis,
            # ring/Ulysses attention as one registered op (context_parallel)
            out = api.sequence_parallel_attention(
                q, k, v, axis_name=self.sep_axis,
                mode=self.sequence_parallel, causal=True)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True,
                dropout_p=self.attn_dropout_p if self.training else 0.0,
                training=self.training,
            )
        out = api.reshape(out, [b, s, h])
        return self.resid_dropout(self.out_proj(out))


class GPTMLP(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.fc_in = ColumnParallelLinear(config.hidden_size, config.intermediate_size, gather_output=False)
        self.fc_out = RowParallelLinear(config.intermediate_size, config.hidden_size, input_is_parallel=True)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, x):
        return self.dropout(self.fc_out(F.gelu(self.fc_in(x), approximate=True)))


class GPTBlock(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(config.hidden_size)
        self.attn = CausalSelfAttention(config)
        self.ln_2 = nn.LayerNorm(config.hidden_size)
        self.mlp = GPTMLP(config)

    def forward(self, x, rope=None, cache=None, pos=None, segments=None):
        if cache is not None:
            a, new_cache = self.attn(self.ln_1(x), rope=rope, cache=cache,
                                     pos=pos)
            x = x + a
            x = x + self.mlp(self.ln_2(x))
            return x, new_cache
        x = x + self.attn(self.ln_1(x), rope=rope, segments=segments)
        x = x + self.mlp(self.ln_2(x))
        return x


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.wte = VocabParallelEmbedding(config.vocab_size, config.hidden_size)
        if not config.use_rotary:
            self.wpe = nn.Embedding(config.max_position_embeddings, config.hidden_size)
        self.drop = nn.Dropout(config.hidden_dropout_prob)
        self.blocks = nn.LayerList([GPTBlock(config) for _ in range(config.num_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size)
        self._rope_cache = None

    def _rope(self, seq_len):
        if self.config.use_rotary:
            import jax.numpy as jnp

            import jax as _jax

            cached = self._rope_cache
            if cached is None or cached[0].shape[0] < seq_len:
                # build once up to max_position_embeddings (llama.py does
                # the same); slicing a cached table beats rebuilding the
                # outer product on every forward / decode step
                d = self.config.hidden_size // self.config.num_heads
                n = max(seq_len, self.config.max_position_embeddings)
                inv = 1.0 / (10000 ** (jnp.arange(0, d, 2,
                                                  dtype=jnp.float32) / d))
                t = jnp.arange(n, dtype=jnp.float32)
                freqs = jnp.outer(t, inv)
                emb = jnp.concatenate([freqs, freqs], axis=-1)
                cached = (jnp.cos(emb), jnp.sin(emb))
                if not isinstance(cached[0], _jax.core.Tracer):
                    # never cache a TRACED table — it would escape the
                    # trace and poison later calls; jit's own cache makes
                    # the traced rebuild free anyway
                    self._rope_cache = cached
            return (Tensor(cached[0][:seq_len]),
                    Tensor(cached[1][:seq_len]))
        return None

    def forward(self, input_ids, caches=None, pos=None, segments=None):
        b, s = input_ids.shape
        h = self.wte(input_ids)
        rope = None
        if caches is not None:
            if segments is not None:
                raise NotImplementedError(
                    "packed (segments=) batches are not supported with "
                    "KV-cache decoding")
            import jax.numpy as jnp
            from jax import lax

            if hasattr(caches[0], "block_table"):
                # paged decode: PER-SLOT positions (each slot is mid-way
                # through its own sequence) ride the packed-rope / gathered
                # wpe form instead of a scalar offset; s > 1 is the
                # speculative verify window at positions seq_lens..+s-1
                pos_v = caches[0].seq_lens
                pos_v = (pos_v._value if isinstance(pos_v, Tensor)
                         else jnp.asarray(pos_v)).astype(jnp.int32)
                pos2d = pos_v[:, None] + jnp.arange(s, dtype=jnp.int32)[None]
                if self.config.use_rotary:
                    cos, sin = self._rope(
                        self.config.max_position_embeddings)
                    rope = (cos, sin, Tensor(pos2d))
                else:
                    h = h + self.wpe(Tensor(pos2d))
                h = self.drop(h)
                new_caches = []
                for block, cache in zip(self.blocks, caches):
                    h, nc = block(h, rope=rope, cache=cache, pos=None)
                    new_caches.append(nc)
                return self.ln_f(h), new_caches
            pos_v = pos._value if isinstance(pos, Tensor) else jnp.asarray(pos)
            pos_v = pos_v.astype(jnp.int32)
            if pos_v.ndim == 1 and pos_v.shape[0] == b:
                # ragged batched prefill (serving engine): each row starts
                # at its OWN offset — per-token positions ride the packed
                # rope / gathered wpe form, and the cached attention op
                # takes the per-row offset vector
                pos2d = pos_v[:, None] + jnp.arange(s, dtype=jnp.int32)[None]
                if self.config.use_rotary:
                    cos, sin = self._rope(
                        self.config.max_position_embeddings)
                    rope = (cos, sin, Tensor(pos2d))
                else:
                    h = h + self.wpe(Tensor(pos2d))
                h = self.drop(h)
                new_caches = []
                for block, cache in zip(self.blocks, caches):
                    h, nc = block(h, rope=rope, cache=cache,
                                  pos=Tensor(pos_v))
                    new_caches.append(nc)
                return self.ln_f(h), new_caches
            pos_v = pos_v.reshape(())
            if self.config.use_rotary:
                cos, sin = self._rope(self.config.max_position_embeddings)
                rope = (Tensor(lax.dynamic_slice(
                            cos._value, (pos_v, 0), (s, cos.shape[-1]))),
                        Tensor(lax.dynamic_slice(
                            sin._value, (pos_v, 0), (s, sin.shape[-1]))))
            else:
                p = api.arange(0, s, 1, dtype="int32") + Tensor(pos_v)
                h = h + self.wpe(p)
            h = self.drop(h)
            new_caches = []
            for block, cache in zip(self.blocks, caches):
                h, nc = block(h, rope=rope, cache=cache, pos=Tensor(pos_v))
                new_caches.append(nc)
            return self.ln_f(h), new_caches
        if segments is not None:
            # positions RESTART at each packed document so a packed row
            # embeds exactly like the same documents padded separately
            import jax.numpy as jnp

            from .generation import packed_positions

            seg_v = (segments._value if isinstance(segments, Tensor)
                     else jnp.asarray(segments)).astype(jnp.int32)
            pos2d = packed_positions(seg_v, s)  # [b, s] per-doc positions
            if self.config.use_rotary:
                # packed rope rides tables + per-token positions; the TPU
                # kernel gathers rows in-kernel (one-hot MXU lookup)
                cos_t, sin_t = self._rope(s)
                rope = (cos_t, sin_t, Tensor(pos2d))
            else:
                h = h + self.wpe(Tensor(pos2d))
        elif self.config.use_rotary:
            rope = self._rope(s)
        else:
            p = api.arange(0, s, 1, dtype="int32")
            h = h + self.wpe(p)
        h = self.drop(h)
        for block in self.blocks:
            if self.config.recompute and self.training:
                from ..distributed.fleet.recompute import recompute

                h = recompute(block, h, rope=rope, segments=segments,
                              policy=self.config.recompute_policy)
            else:
                h = block(h, rope=rope, segments=segments)
        return self.ln_f(h)


class GPTForCausalLM(nn.Layer, GenerationMixin):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(config.hidden_size, config.vocab_size,
                                                has_bias=False, gather_output=True)

    def _decode_geometry(self):
        c = self.config
        return (c.num_layers, c.num_heads, c.hidden_size // c.num_heads,
                c.max_position_embeddings)

    def _head(self, h):
        if self.config.tie_word_embeddings:
            return api.matmul(h, self.gpt.wte.weight, transpose_y=True)
        return self.lm_head(h)

    def forward(self, input_ids, labels=None, caches=None, pos=None,
                segments=None):
        """segments: optional [b, s] packed-document ids (padding -1) —
        the varlen pretrain path (native pack_varlen + segmented
        attention); labels at padding should be -100 (ignored)."""
        if caches is not None:
            if segments is not None:
                raise NotImplementedError(
                    "packed (segments=) batches are not supported with "
                    "KV-cache decoding; generate per document")
            h, new_caches = self.gpt(input_ids, caches=caches, pos=pos)
            return self._head(h), new_caches
        h = self.gpt(input_ids, segments=segments)
        logits = self._head(h)
        if labels is not None:
            # next-token objective: logits[i] predicts labels[i+1]
            # (labels=input_ids is the natural call, as in the reference
            # pretrain pipeline). An unshifted CE here would train the
            # copy task — causal attention sees token i at position i.
            import jax.numpy as jnp

            from ..core.tensor import Tensor

            v = self.config.vocab_size
            shift_logits = api.reshape(logits[:, :-1, :], [-1, v])
            lab = labels._value if isinstance(labels, Tensor) else \
                jnp.asarray(labels)
            shift_lab = lab[:, 1:]
            if segments is not None:
                seg_v = (segments._value if isinstance(segments, Tensor)
                         else jnp.asarray(segments))
                # a pair crossing a packed-document boundary is not a
                # next-token example; padding (-1 segment) masks too
                same_doc = (seg_v[:, 1:] == seg_v[:, :-1]) \
                    & (seg_v[:, 1:] >= 0)
                shift_lab = jnp.where(same_doc, shift_lab, -100)
            loss = F.cross_entropy(shift_logits,
                                   api.reshape(Tensor(shift_lab), [-1]))
            return loss
        return logits


# --------------------------------------------------- pipeline decomposition
class _GPTPipeEmbed(nn.Layer):
    """Stage-0 pre layer: token + positional embedding + dropout, and the
    final LayerNorm that the (tied) head applies — kept here so the
    pipeline's middle stages are HOMOGENEOUS GPTBlocks (the schedule
    engine requires structurally identical stages; embedding/head run
    fused into the first/last stages via SharedLayerDesc)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_position_embeddings,
                                config.hidden_size)
        self.drop = nn.Dropout(config.hidden_dropout_prob)
        if config.tie_word_embeddings:
            # the tied head applies the final norm from this shared layer;
            # untied configs keep ln_f in their own head stage instead
            self.ln_f = nn.LayerNorm(config.hidden_size)

    @property
    def weight(self):
        return self.wte.weight  # the shared (tied) embedding weight

    def forward(self, ids):
        s = ids.shape[1]
        p = api.arange(0, s, 1, dtype="int32")
        return self.drop(self.wte(ids) + self.wpe(p))


class _GPTPipeHead(nn.Layer):
    """Untied head: final norm + projection (shared_post, own weights)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_f = nn.LayerNorm(config.hidden_size)
        self.proj = ColumnParallelLinear(config.hidden_size,
                                         config.vocab_size,
                                         has_bias=False, gather_output=True)

    @property
    def weight(self):
        return self.proj.weight

    def forward(self, h):
        return self.proj(self.ln_f(h))


def _gpt_tied_head_fwd(layer, h):
    return api.matmul(layer.ln_f(h), layer.wte.weight, transpose_y=True)


def _gpt_untied_head_fwd(layer, h):
    return layer(h)


def _gpt_pipeline_loss(out, label):
    # shifted next-token CE, matching GPTForCausalLM.forward so
    # pipeline-vs-sequential parity compares the same objective
    v = out.shape[-1]
    return F.cross_entropy(api.reshape(out[:, :-1, :], [-1, v]),
                           api.reshape(label[:, 1:], [-1]))


def _gpt_pipeline_descs(self):
    """LayerDesc decomposition of this model for pipeline engines
    (reference: PipeLayer desc lists in python/paddle/distributed/fleet/
    meta_parallel/parallel_layers/pp_layers.py; the fleet GPT benchmarks
    build [embedding] + [TransformerLayer]*L + [norm+head] descs).

    Returns (descs, loss_fn, copy_weights) where copy_weights(pipeline_
    layer) copies THIS model's weights into the built pipeline. Rotary
    configs are rejected (rope tables are shared state the desc layers
    don't carry)."""
    from ..distributed.fleet.pipeline_parallel import (
        LayerDesc, SharedLayerDesc)

    cfg = self.config
    if cfg.use_rotary:
        raise ValueError("pipeline_descs: rotary GPT configs are not "
                         "pipeline-decomposable (rope is shared state)")
    descs = [SharedLayerDesc("embed", _GPTPipeEmbed, None, "weight", cfg)]
    descs += [LayerDesc(GPTBlock, cfg) for _ in range(cfg.num_layers)]
    if cfg.tie_word_embeddings:
        descs.append(SharedLayerDesc("embed", _GPTPipeEmbed,
                                     _gpt_tied_head_fwd, "weight", cfg))
    else:
        descs.append(SharedLayerDesc("head", _GPTPipeHead,
                                     _gpt_untied_head_fwd, "weight", cfg))

    model = self

    def copy_weights(pl, reverse=False):
        """model -> pipeline (default) or pipeline -> model (reverse,
        used to sync trained weights back after a pp fit)."""
        pre = pl.shared_pre
        pairs = [(model.gpt.wte.weight, pre.wte.weight),
                 (model.gpt.wpe.weight, pre.wpe.weight)]
        if cfg.tie_word_embeddings:
            pairs += [(model.gpt.ln_f.weight, pre.ln_f.weight),
                      (model.gpt.ln_f.bias, pre.ln_f.bias)]
        for src_blk, dst_blk in zip(model.gpt.blocks, pl.run_function):
            pairs += list(zip(src_blk.parameters(), dst_blk.parameters()))
        if not cfg.tie_word_embeddings:
            head = pl.shared_post[0]
            pairs += [(model.gpt.ln_f.weight, head.ln_f.weight),
                      (model.gpt.ln_f.bias, head.ln_f.bias),
                      (model.lm_head.weight, head.proj.weight)]
        for m_p, p_p in pairs:
            assert tuple(m_p.shape) == tuple(p_p.shape)
            if reverse:
                m_p._value = p_p._value
            else:
                p_p._value = m_p._value

    return descs, _gpt_pipeline_loss, copy_weights


GPTForCausalLM.pipeline_descs = _gpt_pipeline_descs
