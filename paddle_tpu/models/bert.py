"""BERT family (BASELINE configs[2]: BERT-base pretrain, DP + fused attention).

Reference analog: the fleet BERT payloads and fused_attention/
fused_feedforward ops (paddle/fluid/operators/fused/fused_attention_op.cu) —
here the "fusion" is XLA's, with the Pallas flash kernel behind
F.scaled_dot_product_attention for the non-causal path.

Includes the pretraining heads (masked LM + next-sentence prediction) and a
sequence-classification head, mirroring the reference model zoo surface.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..distributed.fleet.mp_layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from ..nn import functional as F
from ..ops import api


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02

    @staticmethod
    def base():
        return BertConfig()

    @staticmethod
    def large():
        return BertConfig(hidden_size=1024, num_layers=24, num_heads=16,
                          intermediate_size=4096)

    @staticmethod
    def tiny():
        return BertConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                          num_heads=4, intermediate_size=256,
                          max_position_embeddings=128,
                          hidden_dropout_prob=0.0, attention_dropout_prob=0.0)


class BertEmbeddings(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        c = config
        self.word_embeddings = VocabParallelEmbedding(c.vocab_size, c.hidden_size)
        self.position_embeddings = nn.Embedding(c.max_position_embeddings, c.hidden_size)
        self.token_type_embeddings = nn.Embedding(c.type_vocab_size, c.hidden_size)
        self.layer_norm = nn.LayerNorm(c.hidden_size, epsilon=c.layer_norm_eps)
        self.dropout = nn.Dropout(c.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None):
        b, s = input_ids.shape
        pos = Tensor(jnp.arange(s, dtype=jnp.int32))
        e = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is not None:
            e = e + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(e))


class BertSelfAttention(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        c = config
        self.num_heads = c.num_heads
        self.head_dim = c.hidden_size // c.num_heads
        self.qkv = ColumnParallelLinear(c.hidden_size, 3 * c.hidden_size,
                                        gather_output=False)
        self.out = RowParallelLinear(c.hidden_size, c.hidden_size,
                                     input_is_parallel=True)
        self.attn_dropout_p = c.attention_dropout_prob
        self.dropout = nn.Dropout(c.hidden_dropout_prob)

    def forward(self, x, attention_mask=None):
        b, s, h = x.shape
        qkv = api.reshape(self.qkv(x), [b, s, self.num_heads, 3 * self.head_dim])
        q, k, v = api.split(qkv, 3, axis=-1)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attention_mask, is_causal=False,
            dropout_p=self.attn_dropout_p if self.training else 0.0,
            training=self.training,
        )
        out = api.reshape(out, [b, s, h])
        return self.dropout(self.out(out))


class BertLayer(nn.Layer):
    """Post-LN encoder block (original BERT ordering)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        c = config
        self.attention = BertSelfAttention(c)
        self.attn_norm = nn.LayerNorm(c.hidden_size, epsilon=c.layer_norm_eps)
        self.fc_in = ColumnParallelLinear(c.hidden_size, c.intermediate_size,
                                          gather_output=False)
        self.fc_out = RowParallelLinear(c.intermediate_size, c.hidden_size,
                                        input_is_parallel=True)
        self.ffn_norm = nn.LayerNorm(c.hidden_size, epsilon=c.layer_norm_eps)
        self.dropout = nn.Dropout(c.hidden_dropout_prob)

    def forward(self, x, attention_mask=None):
        x = self.attn_norm(x + self.attention(x, attention_mask))
        h = self.fc_out(F.gelu(self.fc_in(x), approximate=False))
        return self.ffn_norm(x + self.dropout(h))


class BertModel(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = nn.LayerList([BertLayer(config)
                                     for _ in range(config.num_layers)])
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        """Returns (sequence_output [b,s,h], pooled_output [b,h])."""
        if attention_mask is not None:
            # [b, s] 1/0 -> additive [b, 1, 1, s] broadcastable mask
            m = attention_mask._value.astype(jnp.float32)
            add = (1.0 - m)[:, None, None, :] * -1e9
            attention_mask = Tensor(add)
        h = self.embeddings(input_ids, token_type_ids)
        for layer in self.encoder:
            h = layer(h, attention_mask)
        pooled = api.tanh(self.pooler(h[:, 0]))
        return h, pooled


class BertPretrainingHeads(nn.Layer):
    def __init__(self, config: BertConfig, embedding_weight):
        super().__init__()
        self.transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.transform_norm = nn.LayerNorm(config.hidden_size,
                                           epsilon=config.layer_norm_eps)
        self._embedding_weight = embedding_weight  # tied decoder
        self.decoder_bias = self.create_parameter(
            [config.vocab_size], is_bias=True)
        self.seq_relationship = nn.Linear(config.hidden_size, 2)

    def forward(self, sequence_output, pooled_output):
        h = self.transform_norm(F.gelu(self.transform(sequence_output)))
        mlm_logits = api.matmul(h, api.t(self._embedding_weight)) + self.decoder_bias
        nsp_logits = self.seq_relationship(pooled_output)
        return mlm_logits, nsp_logits


class BertForPretraining(nn.Layer):
    """MLM + NSP (reference pretraining objective)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.bert = BertModel(config)
        self.cls = BertPretrainingHeads(config,
                                        self.bert.embeddings.word_embeddings.weight)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_lm_labels=None, next_sentence_labels=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        mlm_logits, nsp_logits = self.cls(seq, pooled)
        if masked_lm_labels is None:
            return mlm_logits, nsp_logits
        v = mlm_logits.shape[-1]
        mlm_loss = F.cross_entropy(
            api.reshape(mlm_logits, [-1, v]),
            api.reshape(masked_lm_labels, [-1]),
            ignore_index=-100,
        )
        loss = mlm_loss
        if next_sentence_labels is not None:
            loss = loss + F.cross_entropy(nsp_logits,
                                          api.reshape(next_sentence_labels, [-1]))
        return loss


class BertForSequenceClassification(nn.Layer):
    def __init__(self, config: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            return F.cross_entropy(logits, labels)
        return logits
