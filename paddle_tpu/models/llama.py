"""LLaMA family (BASELINE configs[3-4]: LLaMA-2 70B-class sharding-3).

Reference analog: the llama models driven through Paddle's fleet/DistTensor
examples (semi-auto LLaMA in python/paddle/distributed/auto_parallel docs,
fused rope/rms_norm ops at python/paddle/incubate/nn/functional/
fused_rotary_position_embedding.py, rms_norm.py).

TPU-first: pre-norm RMSNorm + SwiGLU + rotary, grouped-query attention
(num_key_value_heads < num_heads repeats K/V — keeps KV cache and HBM traffic
small), bf16-friendly throughout, attention via the Pallas flash kernel path
of F.scaled_dot_product_attention. TP = Column/Row/Vocab parallel shardings;
long context composes with the 'sep' mesh axis (distributed/context_parallel).
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..distributed.fleet.mp_layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from ..nn import functional as F
from ..ops import api
from .generation import GenerationMixin


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_key_value_heads: int = 0  # 0 -> num_heads (MHA); < num_heads -> GQA
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    recompute: bool = False           # activation checkpointing per decoder layer
    recompute_policy: str = None      # jax.checkpoint policy name (None=full)

    def __post_init__(self):
        if not self.num_key_value_heads:
            self.num_key_value_heads = self.num_heads

    @staticmethod
    def llama2_7b():
        return LlamaConfig()

    @staticmethod
    def llama2_70b():
        return LlamaConfig(hidden_size=8192, intermediate_size=28672,
                           num_layers=80, num_heads=64, num_key_value_heads=8)

    @staticmethod
    def tiny():
        return LlamaConfig(vocab_size=512, hidden_size=128, intermediate_size=256,
                           num_layers=2, num_heads=4, num_key_value_heads=2,
                           max_position_embeddings=128)


def _rope_tables(head_dim, max_len, theta, dtype=jnp.float32):
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)  # [S, D/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return Tensor(jnp.cos(emb).astype(dtype)), Tensor(jnp.sin(emb).astype(dtype))


class LlamaAttention(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        c = config
        self.num_heads = c.num_heads
        self.num_kv_heads = c.num_key_value_heads
        self.head_dim = c.hidden_size // c.num_heads
        self.q_proj = ColumnParallelLinear(c.hidden_size, c.num_heads * self.head_dim,
                                           has_bias=False, gather_output=False)
        self.k_proj = ColumnParallelLinear(c.hidden_size, self.num_kv_heads * self.head_dim,
                                           has_bias=False, gather_output=False)
        self.v_proj = ColumnParallelLinear(c.hidden_size, self.num_kv_heads * self.head_dim,
                                           has_bias=False, gather_output=False)
        self.o_proj = RowParallelLinear(c.num_heads * self.head_dim, c.hidden_size,
                                        has_bias=False, input_is_parallel=True)

    def forward(self, x, rope, cache=None, pos=None, segments=None):
        b, s, h = x.shape
        q = api.reshape(self.q_proj(x), [b, s, self.num_heads, self.head_dim])
        k = api.reshape(self.k_proj(x), [b, s, self.num_kv_heads, self.head_dim])
        v = api.reshape(self.v_proj(x), [b, s, self.num_kv_heads, self.head_dim])
        if len(rope) == 3:  # packed: (cos_table, sin_table, pos2d)
            q, k = api.rotary_position_embedding_packed(
                q, k, rope[0], rope[1], rope[2])
        else:
            q, k = api.rotary_position_embedding(q, k, rope[0], rope[1])
        if cache is not None:
            if hasattr(cache, "block_table"):
                # paged decode (serving engine): KV in fixed-size blocks,
                # ragged per-slot lengths; GQA pages keep unrepeated kv heads
                out, new_k, new_v = api.paged_cached_attention(
                    q, k, v, cache.k_pages, cache.v_pages,
                    cache.block_table, cache.seq_lens)
                out = api.reshape(out, [b, s, self.num_heads * self.head_dim])
                return self.o_proj(out), (new_k, new_v)
            # GQA caches keep the UNREPEATED kv heads (HBM = kv_heads/d of
            # MHA); the cached op broadcasts per q-head group at compute time
            out, new_k, new_v = api.cached_multihead_attention(
                q, k, v, cache[0], cache[1], pos)
            out = api.reshape(out, [b, s, self.num_heads * self.head_dim])
            return self.o_proj(out), (new_k, new_v)
        if self.num_kv_heads != self.num_heads:
            rep = self.num_heads // self.num_kv_heads
            k = api.repeat_interleave(k, rep, axis=2)
            v = api.repeat_interleave(v, rep, axis=2)
        if segments is not None:
            # packed-document path (varlen pretrain): attention restricted
            # to each document, causally
            out = api.segmented_attention(q, k, v, segments, causal=True)
        else:
            out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        out = api.reshape(out, [b, s, self.num_heads * self.head_dim])
        return self.o_proj(out)


class LlamaMLP(nn.Layer):
    """SwiGLU: down(silu(gate(x)) * up(x))."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        c = config
        self.gate_proj = ColumnParallelLinear(c.hidden_size, c.intermediate_size,
                                              has_bias=False, gather_output=False)
        self.up_proj = ColumnParallelLinear(c.hidden_size, c.intermediate_size,
                                            has_bias=False, gather_output=False)
        self.down_proj = RowParallelLinear(c.intermediate_size, c.hidden_size,
                                           has_bias=False, input_is_parallel=True)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size,
                                                   epsilon=config.rms_norm_eps)
        self.mlp = LlamaMLP(config)

    def forward(self, x, rope, cache=None, pos=None, segments=None):
        if cache is not None:
            a, new_cache = self.self_attn(self.input_layernorm(x), rope,
                                          cache=cache, pos=pos)
            x = x + a
            x = x + self.mlp(self.post_attention_layernorm(x))
            return x, new_cache
        x = x + self.self_attn(self.input_layernorm(x), rope,
                               segments=segments)
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = VocabParallelEmbedding(config.vocab_size, config.hidden_size)
        self.layers = nn.LayerList([LlamaDecoderLayer(config)
                                    for _ in range(config.num_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        head_dim = config.hidden_size // config.num_heads
        self._rope = _rope_tables(head_dim, config.max_position_embeddings,
                                  config.rope_theta)

    def forward(self, input_ids, caches=None, pos=None, segments=None):
        s = input_ids.shape[1]
        if caches is not None:
            if segments is not None:
                raise NotImplementedError(
                    "packed (segments=) batches are not supported with "
                    "KV-cache decoding")
            from jax import lax

            if hasattr(caches[0], "block_table"):
                # paged decode: per-slot positions via the packed-rope form;
                # s > 1 is the speculative verify window at seq_lens..+s-1
                pos_v = caches[0].seq_lens
                pos_v = (pos_v._value if isinstance(pos_v, Tensor)
                         else jnp.asarray(pos_v)).astype(jnp.int32)
                s = input_ids.shape[1]
                pos2d = pos_v[:, None] + jnp.arange(s, dtype=jnp.int32)[None]
                rope = (self._rope[0], self._rope[1], Tensor(pos2d))
                h = self.embed_tokens(input_ids)
                new_caches = []
                for layer, cache in zip(self.layers, caches):
                    h, nc = layer(h, rope, cache=cache, pos=None)
                    new_caches.append(nc)
                return self.norm(h), new_caches
            pos_v = pos._value if isinstance(pos, Tensor) else jnp.asarray(pos)
            pos_v = pos_v.astype(jnp.int32)
            if pos_v.ndim == 1 and pos_v.shape[0] == input_ids.shape[0]:
                # ragged batched prefill (serving engine): per-row offsets
                # via the packed-rope form; cached attention takes the
                # offset vector
                pos2d = pos_v[:, None] + jnp.arange(s, dtype=jnp.int32)[None]
                rope = (self._rope[0], self._rope[1], Tensor(pos2d))
                h = self.embed_tokens(input_ids)
                new_caches = []
                for layer, cache in zip(self.layers, caches):
                    h, nc = layer(h, rope, cache=cache, pos=Tensor(pos_v))
                    new_caches.append(nc)
                return self.norm(h), new_caches
            pos_v = pos_v.reshape(())
            d = self._rope[0].shape[-1]
            cos = Tensor(lax.dynamic_slice(self._rope[0]._value,
                                           (pos_v, 0), (s, d)))
            sin = Tensor(lax.dynamic_slice(self._rope[1]._value,
                                           (pos_v, 0), (s, d)))
            h = self.embed_tokens(input_ids)
            new_caches = []
            for layer, cache in zip(self.layers, caches):
                h, nc = layer(h, (cos, sin), cache=cache, pos=Tensor(pos_v))
                new_caches.append(nc)
            return self.norm(h), new_caches
        if segments is not None:
            # per-document positions (restart at each packed doc) drive a
            # per-token rope gather -> [b, s, 1, d] broadcast layout
            from .generation import packed_positions

            seg_v = (segments._value if isinstance(segments, Tensor)
                     else jnp.asarray(segments)).astype(jnp.int32)
            pos2d = packed_positions(seg_v, s)
            # slice tables to s (positions are < s): smaller in-kernel
            # lookup and it keeps long-context configs on the kernel path
            rope = (Tensor(self._rope[0]._value[:s]),
                    Tensor(self._rope[1]._value[:s]), Tensor(pos2d))
        else:
            rope = (Tensor(self._rope[0]._value[:s]),
                    Tensor(self._rope[1]._value[:s]))
        h = self.embed_tokens(input_ids)
        for layer in self.layers:
            if self.config.recompute and self.training:
                from ..distributed.fleet.recompute import recompute

                h = recompute(layer, h, rope, segments=segments,
                              policy=self.config.recompute_policy)
            else:
                h = layer(h, rope, segments=segments)
        return self.norm(h)


class LlamaForCausalLM(nn.Layer, GenerationMixin):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.model = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = ColumnParallelLinear(config.hidden_size, config.vocab_size,
                                                has_bias=False)

    def _decode_geometry(self):
        c = self.config
        return (c.num_layers, c.num_key_value_heads,
                c.hidden_size // c.num_heads, c.max_position_embeddings)

    def _head(self, h):
        if self.lm_head is None:
            return api.matmul(h, api.t(self.model.embed_tokens.weight))
        return self.lm_head(h)

    def forward(self, input_ids, labels=None, caches=None, pos=None,
                segments=None):
        """segments: optional [b, s] packed-document ids (padding -1);
        the shifted loss masks pairs that would cross a document
        boundary."""
        if caches is not None:
            if segments is not None:
                raise NotImplementedError(
                    "packed (segments=) batches are not supported with "
                    "KV-cache decoding")
            h, new_caches = self.model(input_ids, caches=caches, pos=pos)
            return self._head(h), new_caches
        h = self.model(input_ids, segments=segments)
        logits = self._head(h)
        if labels is not None:
            b, s, v = logits.shape
            shift_logits = api.reshape(logits[:, :-1, :], [-1, v])
            lab = labels._value if isinstance(labels, Tensor) else \
                jnp.asarray(labels)
            shift_lab = lab[:, 1:]
            if segments is not None:
                seg_v = (segments._value if isinstance(segments, Tensor)
                         else jnp.asarray(segments))
                same_doc = (seg_v[:, 1:] == seg_v[:, :-1]) \
                    & (seg_v[:, 1:] >= 0)  # padding (-1) pairs are not
                #                            next-token examples either
                shift_lab = jnp.where(same_doc, shift_lab, -100)
            shift_labels = api.reshape(Tensor(shift_lab), [-1])
            return F.cross_entropy(shift_logits, shift_labels)
        return logits


# --------------------------------------------------- pipeline decomposition
class _LlamaPipeBlock(nn.Layer):
    """LlamaDecoderLayer with its own rope tables so the stage is
    self-contained (cos/sin recomputed per stage — position-only)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.block = LlamaDecoderLayer(config)
        head_dim = config.hidden_size // config.num_heads
        self._rope = _rope_tables(head_dim, config.max_position_embeddings,
                                  config.rope_theta)

    def forward(self, h):
        s = h.shape[1]
        cos = Tensor(self._rope[0]._value[:s])
        sin = Tensor(self._rope[1]._value[:s])
        return self.block(h, (cos, sin))


class _LlamaPipeEmbed(nn.Layer):
    """Stage-0 pre: token embedding; also holds the final RMSNorm the
    (tied) head applies, keeping middle stages homogeneous."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.embed = VocabParallelEmbedding(config.vocab_size,
                                            config.hidden_size)
        if config.tie_word_embeddings:
            # final norm applied by the tied head; untied configs keep it
            # in their own head stage
            self.norm = nn.RMSNorm(config.hidden_size,
                                   epsilon=config.rms_norm_eps)

    @property
    def weight(self):
        return self.embed.weight

    def forward(self, ids):
        return self.embed(ids)


class _LlamaPipeHead(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.norm = nn.RMSNorm(config.hidden_size,
                               epsilon=config.rms_norm_eps)
        self.proj = ColumnParallelLinear(config.hidden_size,
                                         config.vocab_size, has_bias=False)

    @property
    def weight(self):
        return self.proj.weight

    def forward(self, h):
        return self.proj(self.norm(h))


def _llama_tied_head_fwd(layer, h):
    return api.matmul(layer.norm(h), api.t(layer.embed.weight))


def _llama_untied_head_fwd(layer, h):
    return layer(h)


def _llama_pipeline_loss(out, label):
    v = out.shape[-1]
    shift_logits = api.reshape(out[:, :-1, :], [-1, v])
    shift_labels = api.reshape(label[:, 1:], [-1])
    return F.cross_entropy(shift_logits, shift_labels)


def _llama_pipeline_descs(self):
    """LayerDesc decomposition (see GPTForCausalLM.pipeline_descs).
    Returns (descs, loss_fn, copy_weights)."""
    from ..distributed.fleet.pipeline_parallel import (
        LayerDesc, SharedLayerDesc)

    cfg = self.config
    descs = [SharedLayerDesc("embed", _LlamaPipeEmbed, None, "weight", cfg)]
    descs += [LayerDesc(_LlamaPipeBlock, cfg)
              for _ in range(cfg.num_layers)]
    if cfg.tie_word_embeddings:
        descs.append(SharedLayerDesc("embed", _LlamaPipeEmbed,
                                     _llama_tied_head_fwd, "weight", cfg))
    else:
        descs.append(SharedLayerDesc("head", _LlamaPipeHead,
                                     _llama_untied_head_fwd, "weight", cfg))

    model = self

    def copy_weights(pl, reverse=False):
        """model -> pipeline (default) or pipeline -> model (reverse)."""
        pre = pl.shared_pre
        pairs = [(model.model.embed_tokens.weight, pre.embed.weight)]
        if cfg.tie_word_embeddings:
            pairs.append((model.model.norm.weight, pre.norm.weight))
        for src_l, dst in zip(model.model.layers, pl.run_function):
            pairs += list(zip(src_l.parameters(), dst.block.parameters()))
        if not cfg.tie_word_embeddings:
            head = pl.shared_post[0]
            pairs += [(model.model.norm.weight, head.norm.weight),
                      (model.lm_head.weight, head.proj.weight)]
        for m_p, p_p in pairs:
            assert tuple(m_p.shape) == tuple(p_p.shape)
            if reverse:
                m_p._value = p_p._value
            else:
                p_p._value = m_p._value

    return descs, _llama_pipeline_loss, copy_weights


LlamaForCausalLM.pipeline_descs = _llama_pipeline_descs
