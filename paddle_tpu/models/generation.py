"""Autoregressive generation with static-shape KV caches.

Reference analog: the serving decode path built on the cache-KV variant of
fused_multi_transformer (paddle/fluid/operators/fused/
fused_multi_transformer_op.cu) plus the sampling ops (phi top_p_sampling).

TPU-first design:
  * KV caches are STATIC [b, max_len, kv_heads, head_dim] buffers per layer;
    each decode step writes at `pos` via dynamic_update_slice inside the op
    (ops/kernels/nn_ops.cached_multihead_attention) and masks invalid tail
    positions — so the single-token decode step is ONE compiled XLA program
    reused for every token, with cache buffers donated (updated in place in
    HBM, no reallocation).
  * prefill is a second compiled program per prompt length: it runs the full
    prompt through the same cached path at pos=0, filling the cache in one
    pass.
  * sampling (greedy / temperature / top-k / top-p) happens INSIDE the
    compiled step — no device->host round-trip per token except the optional
    EOS check.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as _random
from ..core.tensor import Tensor


def init_kv_cache(batch: int, max_len: int, num_layers: int,
                  num_kv_heads: int, head_dim: int, dtype=jnp.float32):
    """Allocate the per-layer static KV ring: list of (k, v) arrays."""
    return [
        (jnp.zeros((batch, max_len, num_kv_heads, head_dim), dtype),
         jnp.zeros((batch, max_len, num_kv_heads, head_dim), dtype))
        for _ in range(num_layers)
    ]


def _sample_inside_jit(logits, do_sample, temperature, top_k, top_p, seed):
    """logits: [b, vocab] (last position). Returns ids [b] int32."""
    if not do_sample or (temperature is not None and temperature <= 0.0):
        # temperature 0 conventionally means deterministic decoding
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32)
    if temperature != 1.0:
        logits = logits / temperature
    if top_k:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p and top_p < 1.0:
        from ..ops.kernels.random import nucleus_keep_mask

        order = jnp.argsort(-logits, axis=-1)
        sorted_l = jnp.take_along_axis(logits, order, axis=-1)
        keep_sorted = nucleus_keep_mask(
            jax.nn.softmax(sorted_l, axis=-1), top_p)
        # scatter the keep mask back to vocab order
        keep = jnp.zeros_like(keep_sorted).at[
            jnp.arange(logits.shape[0])[:, None], order].set(keep_sorted)
        logits = jnp.where(keep, logits, -jnp.inf)
    key = jax.random.fold_in(jax.random.PRNGKey(0), seed)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


class GenerationMixin:
    """Adds `generate()` to a CausalLM whose forward supports
    `forward(input_ids, caches=..., pos=...) -> (logits, caches)`.

    Subclass contract (GPTForCausalLM / LlamaForCausalLM):
      * `_decode_geometry() -> (num_layers, num_kv_heads, head_dim, max_pos)`
      * forward threading as above with static-shape caches.
    """

    def _cache_dtype(self):
        p = next(iter(self.parameters()))
        return p._value.dtype

    def _functional_forward(self):
        """A pure fn(param_vals, buffer_vals, ids, caches, pos) ->
        (logits, caches) over this module, safe to jit."""
        params = list(self.parameters())
        buffers = list(self.buffers())

        def fn(param_vals, buffer_vals, ids, caches, pos):
            saved_p = [(p._value, p.stop_gradient) for p in params]
            saved_b = [b._value for b in buffers]
            try:
                for p, v in zip(params, param_vals):
                    p._value = v
                    p.stop_gradient = True
                for b, v in zip(buffers, buffer_vals):
                    b._value = v
                caches_t = [(Tensor(k), Tensor(v)) for k, v in caches]
                logits, new_caches = self.forward(
                    Tensor(ids), caches=caches_t, pos=Tensor(pos))
                return logits._value, [
                    (k._value, v._value) for k, v in new_caches]
            finally:
                for p, (v, sg) in zip(params, saved_p):
                    p._value, p.stop_gradient = v, sg
                for b, v in zip(buffers, saved_b):
                    b._value = v

        return fn, params, buffers

    def generate(self, input_ids, max_new_tokens: int = 32,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None,
                 seed: int = 0):
        """Greedy/sampled autoregressive decoding. Returns the full sequence
        (prompt + generated) as an int32 Tensor [b, s0 + n_new], where n_new
        is max_new_tokens CAPPED at the model's context window
        (max_position_embeddings - prompt_len); the returned tail is also
        truncated early when every row has emitted eos_token_id."""
        was_training = self.training
        self.eval()
        try:
            return self._generate_impl(
                input_ids, max_new_tokens, do_sample, float(temperature),
                int(top_k), float(top_p), eos_token_id, seed)
        finally:
            if was_training:
                self.train()

    def _generate_impl(self, input_ids, max_new_tokens, do_sample,
                       temperature, top_k, top_p, eos_token_id, seed):
        ids = input_ids._value if isinstance(input_ids, Tensor) else jnp.asarray(input_ids)
        ids = ids.astype(jnp.int32)
        b, s0 = ids.shape
        n_layers, n_kv, hd, max_pos = self._decode_geometry()
        max_len = min(int(max_pos), s0 + max_new_tokens)
        n_new = max_len - s0
        if n_new <= 0:
            raise ValueError(
                f"prompt length {s0} leaves no room under "
                f"max_position_embeddings={max_pos}")
        caches = init_kv_cache(b, max_len, n_layers, n_kv, hd,
                               self._cache_dtype())

        fn, params, buffers = self._functional_forward()
        param_vals = [p._value for p in params]
        buffer_vals = [b_._value for b_ in buffers]
        sample_cfg = (bool(do_sample), temperature, top_k, top_p)

        def prefill(pv, bv, ids, caches, step_seed):
            logits, caches = fn(pv, bv, ids, caches, jnp.asarray(0, jnp.int32))
            nxt = _sample_inside_jit(logits[:, -1, :], *sample_cfg, step_seed)
            return nxt, caches

        def decode(pv, bv, tok, caches, pos, step_seed):
            logits, caches = fn(pv, bv, tok[:, None], caches, pos)
            nxt = _sample_inside_jit(logits[:, -1, :], *sample_cfg, step_seed)
            return nxt, caches

        # one compiled program per (prompt_len); one for all decode steps.
        # cache buffers are donated so decode updates KV in place in HBM.
        key_pre = ("_gen_prefill", s0, b, max_len, sample_cfg)
        key_dec = ("_gen_decode", b, max_len, sample_cfg)
        cache = getattr(self, "_gen_exec_cache", None)
        if cache is None:
            cache = self._gen_exec_cache = {}
        if key_pre not in cache:
            cache[key_pre] = jax.jit(prefill, donate_argnums=(3,))
        if key_dec not in cache:
            cache[key_dec] = jax.jit(decode, donate_argnums=(3,))

        tok, caches = cache[key_pre](param_vals, buffer_vals, ids, caches,
                                     jnp.asarray(seed, jnp.int32))
        out: List = [tok]
        eos_rows = None
        if eos_token_id is not None:
            eos_rows = np.asarray(jax.device_get(tok)) == eos_token_id
        for t in range(1, n_new):
            if eos_rows is not None and eos_rows.all():
                break
            tok, caches = cache[key_dec](
                param_vals, buffer_vals, tok, caches,
                jnp.asarray(s0 + t - 1, jnp.int32),
                jnp.asarray(seed + t, jnp.int32))
            if eos_rows is not None:
                # rows already finished are padded with EOS, not with the
                # model's (meaningless) continuation samples
                tok_np = np.where(eos_rows, np.int32(eos_token_id),
                                  np.asarray(jax.device_get(tok)))
                eos_rows |= tok_np == eos_token_id
                tok = jnp.asarray(tok_np)
            out.append(tok)
        return Tensor(jnp.concatenate(
            [ids] + [o[:, None] for o in out], axis=1))


def packed_positions(seg_v, s):
    """Per-document positions for a packed row batch: positions restart
    at every segment boundary (shared by GPT/LLaMA packed paths)."""
    import jax.numpy as jnp
    from jax import lax

    b = seg_v.shape[0]
    ar = jnp.arange(s, dtype=jnp.int32)[None, :]
    new_doc = jnp.concatenate(
        [jnp.ones((b, 1), bool), seg_v[:, 1:] != seg_v[:, :-1]], axis=1)
    starts = lax.cummax(jnp.where(new_doc, ar, 0), axis=1)
    return ar - starts
