"""paddle.regularizer (reference python/paddle/regularizer.py): weight
decay configs consumed by the optimizers' coupled-decay path
(optimizer.py _wd_term)."""
from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class L2Decay:
    """grad += coeff * param."""

    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)
        self.mode = "l2"

    def __repr__(self):
        return f"L2Decay(coeff={self.coeff})"


class L1Decay:
    """grad += coeff * sign(param)."""

    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)
        self.mode = "l1"

    def __repr__(self):
        return f"L1Decay(coeff={self.coeff})"
