"""paddle.profiler analog.

Reference: python/paddle/profiler/profiler.py (Profiler, ProfilerState:79,
ProfilerTarget:99, make_scheduler, export_chrome_tracing:215), RecordEvent
(utils.py), statistics tables (profiler_statistic.py), benchmark timer
(timer.py), over the C++ unified profiler (paddle/fluid/platform/profiler/
profiler.h:47 with HostTracer/CudaTracer plugins).

TPU-native split (SURVEY.md §5.1): host spans come from the native C++ ring-
buffer tracer (paddle_tpu/native/src/tracer.cc — the HostTracer equivalent);
the device timeline belongs to XLA, surfaced by delegating to jax.profiler
(xplane/tensorboard) when a trace_dir is given. Chrome-trace export merges
host spans; statistics aggregate by event name.
"""
from __future__ import annotations

import enum
import json
import os
import time
from typing import Callable, Iterable, Optional

from .. import native
from ..observability import spans as _obs_spans


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1  # accepted for API parity; maps to the device timeline
    TPU = 2
    CUSTOM_DEVICE = 3


class TracerEventType(enum.Enum):
    Operator = 0
    Dataloader = 1
    ProfileStep = 2
    Forward = 3
    Backward = 4
    Optimization = 5
    Communication = 6
    PythonOp = 7
    UserDefined = 8


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """Reference: profiler.py make_scheduler — step-indexed state machine."""
    period = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat > 0 and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def _default_state_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None
                          ) -> Callable:
    """on_trace_ready handler writing chrome://tracing JSON
    (reference: profiler.py:215)."""

    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        worker = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{worker}_step{prof.step_num}.json")
        prof.export(path, format="json")
        prof.last_export_path = path

    return handler


def export_protobuf(dir_name: str, worker_name: Optional[str] = None) -> Callable:
    """API-parity handler (reference exports a protobuf dump); emits the same
    chrome JSON payload with a .pb.json suffix."""

    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        worker = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{worker}_step{prof.step_num}.pb.json")
        prof.export(path, format="json")
        prof.last_export_path = path

    return handler


class RecordEvent:
    """User-annotated host span (reference: paddle.profiler.RecordEvent).

    Falls back to the pure-Python span ring (observability/spans.py) when the
    native library is absent: spans recorded between Profiler.start/stop are
    collected from that ring and merged into the exported chrome trace, so
    annotations survive on hosts without the C++ tracer (r6–r8 silently
    dropped them). Outside a recording context the fallback is a no-op, same
    as the native tracer when disabled.
    """

    def __init__(self, name: str, event_type: TracerEventType = TracerEventType.UserDefined):
        self.name = name
        self.event_type = event_type
        self._begun = False
        self._t0 = 0

    def begin(self):
        self._t0 = 0
        if native.available():
            native.trace_push(self.name)
        elif _obs_spans.enabled():
            self._t0 = time.monotonic_ns()
        self._begun = True

    def end(self):
        if self._begun:
            if native.available():
                native.trace_pop()
            elif self._t0:
                _obs_spans.record_span(self.name, self._t0,
                                       time.monotonic_ns(), cat="user")
        self._begun = False
        self._t0 = 0

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def load_profiler_result(path: str):
    with open(path) as f:
        return json.load(f)


class _EventStat:
    __slots__ = ("name", "calls", "total_ns", "max_ns", "min_ns")

    def __init__(self, name):
        self.name = name
        self.calls = 0
        self.total_ns = 0
        self.max_ns = 0
        self.min_ns = 1 << 62

    def add(self, dur):
        self.calls += 1
        self.total_ns += dur
        self.max_ns = max(self.max_ns, dur)
        self.min_ns = min(self.min_ns, dur)

    @property
    def avg_ns(self):
        return self.total_ns // max(self.calls, 1)


class Profiler:
    """Reference: paddle.profiler.Profiler — start/stop/step driven by a
    scheduler; on RECORD_AND_RETURN boundaries the on_trace_ready handler
    fires with the collected spans."""

    def __init__(self, *, targets: Optional[Iterable[ProfilerTarget]] = None,
                 scheduler=None, on_trace_ready: Optional[Callable] = None,
                 timer_only: bool = False, record_shapes: bool = False,
                 profile_memory: bool = False, with_flops: bool = False):
        self.targets = list(targets) if targets else [ProfilerTarget.CPU]
        if scheduler is None:
            self._scheduler = _default_state_scheduler
        elif isinstance(scheduler, (tuple, list)):
            start, end = scheduler
            self._scheduler = make_scheduler(
                closed=max(start, 0), ready=0, record=end - start, repeat=1)
        else:
            self._scheduler = scheduler
        self.on_trace_ready = on_trace_ready or (lambda prof: None)
        self.timer_only = timer_only
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self.last_export_path = None
        self._spans = []
        self._benchmark = _Benchmark()
        self._recording = False
        self._device_trace_dir = None
        self._last_device_dir = None   # kept after stop for export merge
        self._clock_sync = None        # (host steady_ns, epoch_ns) pair
        self._span_mark = 0            # python span-ring watermark (fallback)

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self._benchmark.begin()
        if self.timer_only:
            return
        self.current_state = self._scheduler(self.step_num)
        if self.current_state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            self._start_record()

    def stop(self):
        self._benchmark.end()
        if self.timer_only:
            return
        if self._recording:
            self._stop_record()
            self.on_trace_ready(self)

    def step(self, num_samples: Optional[int] = None):
        """Advance the scheduler one training step."""
        self._benchmark.step(num_samples)
        if self.timer_only:
            self.step_num += 1
            return
        prev = self.current_state
        self.step_num += 1
        new = self._scheduler(self.step_num)
        if prev == ProfilerState.RECORD_AND_RETURN and self._recording:
            self._stop_record()
            self.on_trace_ready(self)
        if new in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN) and not self._recording:
            self._start_record()
        elif new == ProfilerState.CLOSED and self._recording and prev != ProfilerState.RECORD_AND_RETURN:
            self._stop_record()
            self.on_trace_ready(self)
        self.current_state = new

    def step_info(self, unit: Optional[str] = None) -> str:
        return self._benchmark.step_info(unit)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- recording ---------------------------------------------------------
    def _start_record(self):
        # a fresh session must not inherit the previous session's device
        # dump or clock pair — export() would merge stale device lanes
        self._last_device_dir = None
        self._clock_sync = None
        if native.available():
            native.trace_clear()
            native.trace_enable(True)
        else:
            # pure-Python fallback: open a span-ring session and note the
            # watermark — stop collects everything recorded after it
            _obs_spans.session(True)
            self._span_mark = _obs_spans.mark()
        if ProfilerTarget.TPU in self.targets or ProfilerTarget.GPU in self.targets:
            # device timeline is XLA's: delegate to jax.profiler (xplane)
            try:
                import jax

                self._device_trace_dir = os.environ.get(
                    "PADDLE_TPU_TRACE_DIR", "/tmp/paddle_tpu_xplane")
                jax.profiler.start_trace(self._device_trace_dir)
                # clock-correspondence sample: host spans are steady_clock
                # ns, xplane timestamps are epoch ns — one paired reading
                # lets export() place both on a single axis
                steady = (native.trace_now_ns() if native.available()
                          else time.monotonic_ns())
                self._clock_sync = (steady, time.time_ns())
            except Exception:
                self._device_trace_dir = None
        self._recording = True

    def _stop_record(self):
        if native.available():
            self._spans = native.trace_spans()
            native.trace_enable(False)
        else:
            self._spans = _obs_spans.since(self._span_mark)
            _obs_spans.session(False)
        if self._device_trace_dir is not None:
            try:
                import jax

                jax.profiler.stop_trace()
                self._last_device_dir = self._device_trace_dir
            except Exception:
                pass
            self._device_trace_dir = None
        self._recording = False

    # -- export / stats ----------------------------------------------------
    def export(self, path: str, format: str = "json"):
        """One chrome trace: host spans + the XLA device timeline (parsed
        from the jax.profiler xplane protobufs) on a shared time axis —
        the reference's host+CUPTI merged chrome_tracing_logger, TPU-style
        (SURVEY §5.1)."""
        from .xplane import merged_chrome_trace

        events = merged_chrome_trace(self._spans, self._last_device_dir,
                                     self._clock_sync)
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)

    def events(self):
        return list(self._spans)

    def summary(self, sorted_by="total", op_detail=True, thread_sep=False,
                time_unit="ms") -> str:
        """Aggregate spans by name (reference: profiler_statistic.py tables)."""
        stats = {}
        for s in self._spans:
            st = stats.get(s["name"])
            if st is None:
                st = stats[s["name"]] = _EventStat(s["name"])
            st.add(s["end_ns"] - s["begin_ns"])
        div = {"s": 1e9, "ms": 1e6, "us": 1e3, "ns": 1.0}[time_unit]
        rows = sorted(stats.values(), key=lambda st: -st.total_ns)
        lines = [
            f"{'Name':<40} {'Calls':>8} {'Total(' + time_unit + ')':>14} "
            f"{'Avg(' + time_unit + ')':>12} {'Max(' + time_unit + ')':>12}"
        ]
        for st in rows:
            lines.append(
                f"{st.name:<40} {st.calls:>8} {st.total_ns / div:>14.3f} "
                f"{st.avg_ns / div:>12.3f} {st.max_ns / div:>12.3f}"
            )
        return "\n".join(lines)


class _Benchmark:
    """Reader-cost / ips tracker (reference: profiler/timer.py Benchmark)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._step_start = None
        self._steps = 0
        self._total_time = 0.0
        self._samples = 0

    def begin(self):
        self._step_start = time.perf_counter()

    def end(self):
        pass

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._step_start is not None:
            self._total_time += now - self._step_start
            self._steps += 1
            if num_samples:
                self._samples += num_samples
        self._step_start = now

    def step_info(self, unit=None):
        if self._steps == 0:
            return "no steps recorded"
        avg = self._total_time / self._steps
        msg = f"avg_step_time: {avg * 1e3:.3f} ms"
        if self._samples:
            ips = self._samples / self._total_time
            msg += f" ips: {ips:.1f} {unit or 'samples'}/s"
        return msg


class benchmark:
    """paddle.profiler.benchmark() — module-level timer facade."""

    _inst = _Benchmark()

    @classmethod
    def begin(cls):
        cls._inst.begin()

    @classmethod
    def step(cls, num_samples=None):
        cls._inst.step(num_samples)

    @classmethod
    def step_info(cls, unit=None):
        return cls._inst.step_info(unit)

    @classmethod
    def reset(cls):
        cls._inst.reset()


__all__ = [
    "Profiler",
    "ProfilerState",
    "ProfilerTarget",
    "TracerEventType",
    "RecordEvent",
    "make_scheduler",
    "export_chrome_tracing",
    "export_protobuf",
    "load_profiler_result",
    "benchmark",
]


class SortedKeys(enum.Enum):
    """Summary-table sort orders (reference profiler/profiler_statistic.py)."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView(enum.Enum):
    """Summary report views (reference profiler/profiler.py SummaryView)."""

    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8
