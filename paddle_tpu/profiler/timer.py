"""Reader/step cost accounting (reference: python/paddle/profiler/timer.py —
Benchmark, reader_cost / batch_cost / ips).

`benchmark()` returns the process-wide Benchmark. DataLoader iterators report
the time they spend blocked producing each batch (reader_cost); training
loops call `step(n_samples)` after each optimizer step so batch_cost and ips
(samples/sec) come out of the same clock. A reader_cost close to batch_cost
means the input pipeline — not the device — is the bottleneck.
"""
from __future__ import annotations

import time


class _Avg:
    __slots__ = ("total", "count", "last")

    def __init__(self):
        self.total = 0.0
        self.count = 0
        self.last = 0.0

    def update(self, v):
        self.total += v
        self.count += 1
        self.last = v

    @property
    def avg(self):
        return self.total / self.count if self.count else 0.0


class Benchmark:
    def __init__(self):
        self.reset()

    def reset(self):
        self.reader = _Avg()
        self.batch = _Avg()
        self._samples = 0
        self._step_start = None

    # --- reader side (called by DataLoader iterators) -----------------------
    def record_reader(self, seconds):
        self.reader.update(seconds)
        if self._step_start is None:
            self._step_start = time.perf_counter()

    # --- training-loop side -------------------------------------------------
    def step(self, num_samples=None):
        """Mark one optimizer step; batch_cost spans step->step."""
        now = time.perf_counter()
        if self._step_start is not None:
            self.batch.update(now - self._step_start)
        self._step_start = now
        if num_samples:
            self._samples += num_samples

    @property
    def reader_cost(self):
        return self.reader.avg

    @property
    def batch_cost(self):
        return self.batch.avg

    @property
    def ips(self):
        """Average samples/sec over recorded steps."""
        t = self.batch.total
        return self._samples / t if t > 0 else 0.0

    def summary(self):
        return {
            "reader_cost_avg_s": round(self.reader.avg, 6),
            "batch_cost_avg_s": round(self.batch.avg, 6),
            "ips": round(self.ips, 2),
            "reader_fraction": round(
                self.reader.avg / self.batch.avg, 4) if self.batch.count else 0.0,
        }


_benchmark = Benchmark()


def benchmark() -> Benchmark:
    return _benchmark
