"""Device-timeline reader: parse the XPlane protobufs jax.profiler writes
and merge them with the native host-span trace into ONE chrome trace.

Reference: the reference profiler merges host-side RecordEvents with the
CUPTI device timeline into a single chrome trace
(paddle/fluid/platform/profiler/chrome_tracing_logger.cc); on TPU the
device timeline comes from XLA's profiler (xplane), so the merge reads the
public XSpace schema via the checked-in minimal protobuf
(xplane_minimal.proto).

Clock mapping: host spans carry steady_clock ns (native/src/tracer.cc
now_ns); xplane line timestamps are epoch ns (TSL NowNanos). The profiler
records a (steady_ns, epoch_ns) pair at record start; device events map
onto the host timeline via that correspondence (same process, sub-ms skew).
"""
from __future__ import annotations

import glob
import os
from typing import Dict, Iterator, List, Optional, Tuple


def find_xplane_files(trace_dir: str) -> List[str]:
    """jax.profiler writes <dir>/plugins/profile/<run>/<host>.xplane.pb,
    one timestamped <run> per session. Only the NEWEST run belongs to the
    profiler session that exported — older runs (or other processes using
    the same dir) must not leak stale device lanes into the merge."""
    runs = sorted(glob.glob(os.path.join(trace_dir, "plugins", "profile",
                                         "*")),
                  key=os.path.getmtime)
    if not runs:
        return []
    return sorted(glob.glob(os.path.join(runs[-1], "*.xplane.pb")))


def load_xspace(path: str):
    from . import xplane_minimal_pb2 as pb

    space = pb.XSpace()
    with open(path, "rb") as f:
        space.ParseFromString(f.read())
    return space


def device_events(trace_dir: str) -> Iterator[Dict]:
    """Yield {plane, line, name, start_ns (epoch), dur_ns} for every event
    on every plane of every xplane file under trace_dir."""
    for path in find_xplane_files(trace_dir):
        space = load_xspace(path)
        for plane in space.planes:
            meta = {m_id: m.display_name or m.name
                    for m_id, m in plane.event_metadata.items()}
            for line in plane.lines:
                lname = line.display_name or line.name or f"line{line.id}"
                for ev in line.events:
                    yield {
                        "plane": plane.name,
                        "line": lname,
                        "name": meta.get(ev.metadata_id,
                                         f"event{ev.metadata_id}"),
                        "start_ns": line.timestamp_ns + ev.offset_ps // 1000,
                        "dur_ns": max(ev.duration_ps // 1000, 1),
                    }


def merged_chrome_trace(host_spans: List[Dict],
                        trace_dir: Optional[str],
                        sync: Optional[Tuple[int, int]]) -> List[Dict]:
    """Build chrome-trace events: host spans on pid 'host', device planes on
    one pid per plane, all on the host steady-clock axis (µs).

    sync = (steady_ns, epoch_ns) captured together at record start."""
    events: List[Dict] = []
    pid = os.getpid()
    for s in host_spans:
        events.append({
            "name": s["name"], "ph": "X", "pid": pid, "tid": s["tid"],
            "ts": s["begin_ns"] / 1e3,
            "dur": (s["end_ns"] - s["begin_ns"]) / 1e3, "cat": "host",
        })
    events.append({"name": "process_name", "ph": "M", "pid": pid,
                   "args": {"name": "host"}})
    if trace_dir is None:
        return events
    steady0, epoch0 = sync if sync else (0, 0)
    # group per plane: XLA planes disagree on time base (host planes use
    # epoch ns, some device planes are session-relative). Epoch-based lines
    # map exactly through the sync pair; anything else anchors its earliest
    # event at record start — lanes stay internally exact either way.
    per_plane: Dict[str, List[Dict]] = {}
    for ev in device_events(trace_dir):
        per_plane.setdefault(ev["plane"], []).append(ev)
    plane_pid = pid + 1000
    for plane, evs in per_plane.items():
        events.append({"name": "process_name", "ph": "M", "pid": plane_pid,
                       "args": {"name": f"device:{plane}"}})
        base = min(e["start_ns"] for e in evs)
        epoch_based = sync and abs(base - epoch0) < 3600 * 1e9  # within 1h
        for ev in evs:
            if epoch_based:
                start_steady = ev["start_ns"] - epoch0 + steady0
            elif sync:
                start_steady = ev["start_ns"] - base + steady0
            else:
                start_steady = ev["start_ns"] - base
            events.append({
                "name": ev["name"], "ph": "X", "pid": plane_pid,
                "tid": ev["line"], "ts": start_steady / 1e3,
                "dur": ev["dur_ns"] / 1e3, "cat": "device",
            })
        plane_pid += 1
    return events
