"""Reference-ecosystem checkpoint interop: load published Paddle
`*.pdparams` state dicts into this framework's model zoo.

Reference format: `paddle.save(model.state_dict(), 'm.pdparams')` pickles
a {structured_name: ndarray} dict (python/paddle/framework/io.py save —
tensors are converted to numpy before pickling). This framework's layers
already follow the reference's parameter conventions (Linear [in, out],
Conv OIHW, BatchNorm `_mean`/`_variance` buffers in the state dict), so
vision checkpoints map near-1:1; NLP checkpoints from the PaddleNLP
ecosystem need structural renames plus a q/k/v -> fused-qkv weave (this
zoo fuses attention projections; the per-head column layout is
[q_h | k_h | v_h] per head — see models/bert.py BertSelfAttention).

Name aliasing follows the compat tables the reference keeps in
paddle/phi/api/yaml/op_compat.yaml (e.g. batch_norm Mean/Variance ->
mean/variance, fluid-era `.w_0`/`.b_0` parameter suffixes).
"""
from __future__ import annotations

import io
import pickle
import re
from typing import Dict, List, Optional

import numpy as np

__all__ = [
    "load_pdparams", "save_pdparams", "convert_paddle_state_dict",
    "load_paddle_checkpoint",
]


# ------------------------------------------------------------- pickle IO
class _RestrictedUnpickler(pickle.Unpickler):
    """Unpickle only what a pdparams state dict legitimately contains."""

    _ALLOWED = {
        ("numpy", "ndarray"), ("numpy", "dtype"),
        ("numpy.core.multiarray", "_reconstruct"),
        ("numpy.core.multiarray", "scalar"),
        ("numpy._core.multiarray", "_reconstruct"),
        ("numpy._core.multiarray", "scalar"),
        ("collections", "OrderedDict"),
        ("_codecs", "encode"),  # numpy pickles bytes via _codecs.encode
    }

    def find_class(self, module, name):
        # strict allowlist only: a module prefix check would admit exec
        # gadgets like numpy.testing._private.utils.runstring
        if (module, name) in self._ALLOWED:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"pdparams: refusing to unpickle {module}.{name}")


def load_pdparams(path: str) -> Dict[str, np.ndarray]:
    """Read a reference-format `.pdparams` file into {name: ndarray}."""
    with open(path, "rb") as f:
        obj = _RestrictedUnpickler(f).load()
    if not isinstance(obj, dict):
        raise ValueError(f"pdparams: expected a state dict, got {type(obj)}")
    return {str(k): np.asarray(v) for k, v in obj.items()}


def save_pdparams(state_dict, path: str) -> None:
    """Write a reference-compatible `.pdparams` (numpy-valued pickle)."""
    out = {}
    for k, v in state_dict.items():
        v = getattr(v, "_value", v)
        out[str(k)] = np.asarray(v)
    with open(path, "wb") as f:
        pickle.dump(out, f, protocol=2)


# ------------------------------------------------------ name conversion
# fluid-era parameter suffixes (op_compat.yaml-era compat: linear/conv
# parameters were published as `<op>_<i>.w_0` / `.b_0`)
_FLUID_SUFFIXES = [(re.compile(r"\.w_0$"), ".weight"),
                   (re.compile(r"\.b_0$"), ".bias")]
# NOTE: .w_1/.b_1 deliberately do NOT alias to .weight/.bias — a scope
# with both w_0 and w_1 holds two DISTINCT parameters, and collapsing
# them would silently drop one; unmatched w_1 keys surface as
# 'unexpected' so the caller sees them

# batch_norm compat (op_compat.yaml: batch_norm {Scale: scale, Bias:
# bias, Mean: mean, Variance: variance}); published vision state dicts
# use `_mean`/`_variance`, older exports `.mean`/`.variance`
_BN_ALIASES = [(re.compile(r"\.mean$"), "._mean"),
               (re.compile(r"\.variance$"), "._variance"),
               (re.compile(r"\.moving_mean$"), "._mean"),
               (re.compile(r"\.moving_variance$"), "._variance")]


def _apply_aliases(name: str) -> str:
    for pat, rep in _FLUID_SUFFIXES + _BN_ALIASES:
        name = pat.sub(rep, name)
    return name


def _weave_qkv(wq, wk, wv, num_heads: int, axis: int):
    """Concatenate separate q/k/v projections into the fused per-head
    layout [q_h | k_h | v_h] used by this zoo's attention blocks."""
    H = wq.shape[axis]
    hd = H // num_heads
    parts = []
    for arr in (wq, wk, wv):
        shape = list(arr.shape)
        shape[axis:axis + 1] = [num_heads, hd]
        parts.append(arr.reshape(shape))
    woven = np.stack(parts, axis=axis + 1)  # [..., heads, 3, hd, ...]
    shape = list(wq.shape)
    shape[axis] = 3 * H
    return woven.reshape(shape)


def _unweave_qkv(w, num_heads: int, axis: int):
    """Inverse of _weave_qkv (used to EXPORT back to q/k/v checkpoints)."""
    H3 = w.shape[axis]
    H = H3 // 3
    hd = H // num_heads
    shape = list(w.shape)
    shape[axis:axis + 1] = [num_heads, 3, hd]
    woven = w.reshape(shape)
    outs = []
    for i in range(3):
        part = np.take(woven, i, axis=axis + 1)
        shape = list(w.shape)
        shape[axis] = H
        outs.append(part.reshape(shape))
    return outs


def _convert_bert(sd: Dict[str, np.ndarray],
                  num_heads: Optional[int] = None) -> Dict[str, np.ndarray]:
    """PaddleNLP bert naming -> this zoo's BertModel naming.

    PaddleNLP (transformers.bert.modeling.BertModel over
    nn.TransformerEncoder): bert.embeddings.*,
    bert.encoder.layers.{i}.self_attn.{q,k,v}_proj / out_proj,
    .linear1/.linear2, .norm1/.norm2, bert.pooler.dense.
    """
    sd = {re.sub(r"^bert\.", "", k): v for k, v in sd.items()}
    out: Dict[str, np.ndarray] = {}
    # gather q/k/v triplets per layer for the weave
    qkv: Dict[str, Dict[str, np.ndarray]] = {}
    renames = [
        (re.compile(r"^encoder\.layers\.(\d+)\.self_attn\.out_proj\."),
         r"encoder.\1.attention.out."),
        (re.compile(r"^encoder\.layers\.(\d+)\.linear1\."),
         r"encoder.\1.fc_in."),
        (re.compile(r"^encoder\.layers\.(\d+)\.linear2\."),
         r"encoder.\1.fc_out."),
        (re.compile(r"^encoder\.layers\.(\d+)\.norm1\."),
         r"encoder.\1.attn_norm."),
        (re.compile(r"^encoder\.layers\.(\d+)\.norm2\."),
         r"encoder.\1.ffn_norm."),
        (re.compile(r"^pooler\.dense\."), "pooler."),
    ]
    for k, v in sd.items():
        m = re.match(r"^encoder\.layers\.(\d+)\.self_attn\."
                     r"([qkv])_proj\.(weight|bias)$", k)
        if m:
            qkv.setdefault(f"{m.group(1)}.{m.group(3)}", {})[m.group(2)] = v
            continue
        nk = k
        for pat, rep in renames:
            nk = pat.sub(rep, nk)
        out[nk] = v
    for key, triple in qkv.items():
        layer, kind = key.split(".")
        if set(triple) != {"q", "k", "v"}:
            raise ValueError(f"bert convert: incomplete q/k/v for layer "
                             f"{layer} ({sorted(triple)})")
        wq = triple["q"]
        heads = num_heads
        if heads is None:
            raise ValueError("bert convert: num_heads required to weave "
                             "q/k/v into the fused layout")
        axis = 1 if kind == "weight" else 0
        out[f"encoder.{layer}.attention.qkv.{kind}"] = _weave_qkv(
            triple["q"], triple["k"], triple["v"], heads, axis)
    return out


def _export_bert(sd: Dict[str, np.ndarray],
                 num_heads: int) -> Dict[str, np.ndarray]:
    """This zoo's BertModel naming -> PaddleNLP naming (inverse)."""
    out: Dict[str, np.ndarray] = {}
    renames = [
        (re.compile(r"^encoder\.(\d+)\.attention\.out\."),
         r"encoder.layers.\1.self_attn.out_proj."),
        (re.compile(r"^encoder\.(\d+)\.fc_in\."),
         r"encoder.layers.\1.linear1."),
        (re.compile(r"^encoder\.(\d+)\.fc_out\."),
         r"encoder.layers.\1.linear2."),
        (re.compile(r"^encoder\.(\d+)\.attn_norm\."),
         r"encoder.layers.\1.norm1."),
        (re.compile(r"^encoder\.(\d+)\.ffn_norm\."),
         r"encoder.layers.\1.norm2."),
        (re.compile(r"^pooler\."), "pooler.dense."),
    ]
    for k, v in sd.items():
        m = re.match(r"^encoder\.(\d+)\.attention\.qkv\.(weight|bias)$", k)
        if m:
            axis = 1 if m.group(2) == "weight" else 0
            q, kk, vv = _unweave_qkv(np.asarray(v), num_heads, axis)
            for nm, arr in (("q", q), ("k", kk), ("v", vv)):
                out[f"bert.encoder.layers.{m.group(1)}.self_attn."
                    f"{nm}_proj.{m.group(2)}"] = arr
            continue
        nk = k
        for pat, rep in renames:
            nk = pat.sub(rep, nk)
        out["bert." + nk] = np.asarray(getattr(v, "_value", v))
    return out


def convert_paddle_state_dict(sd: Dict[str, np.ndarray], model=None,
                              family: Optional[str] = None,
                              num_heads: Optional[int] = None
                              ) -> Dict[str, np.ndarray]:
    """Map a reference-ecosystem state dict onto this zoo's names.

    family: 'bert' (PaddleNLP naming, q/k/v weave), or None for the
    near-identity vision mapping (alias fixups only). Auto-detected from
    key fingerprints when None and a bert-style dict is given."""
    if family is None:
        if any(".self_attn.q_proj." in k for k in sd):
            family = "bert"
    if family == "bert":
        if num_heads is None and model is not None:
            num_heads = getattr(getattr(model, "config", None),
                                "num_heads", None)
        return _convert_bert(sd, num_heads=num_heads)
    return {_apply_aliases(k): v for k, v in sd.items()}


def load_paddle_checkpoint(model, path: str, family: Optional[str] = None,
                           strict: bool = True) -> List[str]:
    """Load a `.pdparams` checkpoint into `model`. Returns the list of
    checkpoint keys that did not match any model state (empty when
    strict, or raises)."""
    sd = load_pdparams(path)
    conv = convert_paddle_state_dict(sd, model=model, family=family)
    own = model.state_dict()
    missing = [k for k in own if k not in conv]
    unexpected = [k for k in conv if k not in own]
    if strict and (missing or unexpected):
        raise ValueError(
            f"load_paddle_checkpoint: missing={missing[:8]} "
            f"unexpected={unexpected[:8]} "
            f"(of {len(missing)}/{len(unexpected)})")
    for k, v in conv.items():
        if k in own:
            cur = own[k]
            if tuple(np.shape(v)) != tuple(cur.shape):
                raise ValueError(
                    f"load_paddle_checkpoint: shape mismatch for {k}: "
                    f"checkpoint {np.shape(v)} vs model {tuple(cur.shape)}")
    model.set_state_dict({k: v for k, v in conv.items() if k in own})
    return unexpected


def export_paddle_state_dict(model, family: Optional[str] = None,
                             num_heads: Optional[int] = None
                             ) -> Dict[str, np.ndarray]:
    """Export `model`'s state dict under reference-ecosystem names (the
    inverse mapping; useful for round-trip tests and for publishing
    checkpoints consumable by reference tooling)."""
    sd = {k: np.asarray(getattr(v, "_value", v))
          for k, v in model.state_dict().items()}
    if family == "bert":
        heads = num_heads or getattr(getattr(model, "config", None),
                                     "num_heads", None)
        return _export_bert(sd, heads)
    return sd
