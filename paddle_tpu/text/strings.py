"""StringTensor + string kernels.

Reference: paddle/phi/core/string_tensor.h (a TensorBase holding pstring
elements) and paddle/phi/kernels/strings/ — strings_lower_upper_kernel.h
(ASCII + UTF-8 case mapping via case_utils.h/unicode.h), strings_copy,
strings_empty.

TPU-native placement: strings are HOST data (no accelerator dtype exists);
a StringTensor is a shaped numpy object array living on the host, and the
string kernels are vectorized host ops. The boundary to device compute is
explicit: `encode`/`lookup` produce int32 Tensors (token ids) that enter
the jax world, which is exactly how the reference's data pipeline feeds
string features into kernels.
"""
from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import numpy as np

from ..core.tensor import Tensor


class StringTensor:
    """Shaped host tensor of python strings (reference StringTensor)."""

    def __init__(self, data, shape: Optional[Sequence[int]] = None):
        arr = np.asarray(data, dtype=object)
        if shape is not None:
            arr = arr.reshape(tuple(shape))
        self._data = arr

    @property
    def shape(self):
        return tuple(self._data.shape)

    def numel(self) -> int:
        return int(self._data.size)

    def numpy(self) -> np.ndarray:
        return self._data

    def tolist(self):
        return self._data.tolist()

    def reshape(self, shape):
        return StringTensor(self._data.reshape(tuple(shape)))

    def __getitem__(self, i):
        out = self._data[i]
        return StringTensor(out) if isinstance(out, np.ndarray) else out

    def __eq__(self, other):
        o = other._data if isinstance(other, StringTensor) else other
        return Tensor(np.asarray(self._data == o))

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, data={self._data!r})"


def _map(x: StringTensor, fn) -> StringTensor:
    flat = [fn(s) for s in x._data.ravel()]
    return StringTensor(np.asarray(flat, object).reshape(x._data.shape))


def to_string_tensor(data) -> StringTensor:
    return data if isinstance(data, StringTensor) else StringTensor(data)


# -------------------------------------------------------- string kernels
def lower(x, use_utf8_encoding: bool = True) -> StringTensor:
    """strings_lower_upper_kernel StringLowerKernel: python str.lower is
    the full Unicode case map; ASCII-only mode mirrors the reference's
    non-utf8 path."""
    x = to_string_tensor(x)
    if use_utf8_encoding:
        return _map(x, str.lower)
    return _map(x, lambda s: "".join(
        c.lower() if ord(c) < 128 else c for c in s))


def upper(x, use_utf8_encoding: bool = True) -> StringTensor:
    x = to_string_tensor(x)
    if use_utf8_encoding:
        return _map(x, str.upper)
    return _map(x, lambda s: "".join(
        c.upper() if ord(c) < 128 else c for c in s))


def length(x) -> Tensor:
    x = to_string_tensor(x)
    return Tensor(np.asarray([len(s) for s in x._data.ravel()],
                             np.int64).reshape(x._data.shape))


def strip(x) -> StringTensor:
    return _map(to_string_tensor(x), str.strip)


def join(x, sep: str = "") -> str:
    return sep.join(to_string_tensor(x)._data.ravel().tolist())


def split(x, sep: Optional[str] = None) -> List[List[str]]:
    x = to_string_tensor(x)
    return [s.split(sep) for s in x._data.ravel()]


def concat(xs: Iterable, axis: int = 0) -> StringTensor:
    arrs = [to_string_tensor(x)._data for x in xs]
    return StringTensor(np.concatenate(arrs, axis=axis))


def starts_with(x, prefix: str) -> Tensor:
    x = to_string_tensor(x)
    return Tensor(np.asarray([s.startswith(prefix)
                              for s in x._data.ravel()],
                             bool).reshape(x._data.shape))


# ------------------------------------------------- string -> id boundary
class Vocab:
    """Token <-> id mapping (reference: the tokenizer-side vocab consumed
    by faster_tokenizer; minimal core without the C++ tokenizer runtime)."""

    def __init__(self, tokens: Sequence[str], unk_token: str = "[UNK]"):
        self.unk_token = unk_token
        toks = list(tokens)
        if unk_token not in toks:
            toks = [unk_token] + toks
        self._id = {t: i for i, t in enumerate(toks)}
        self._tok = toks

    def __len__(self):
        return len(self._tok)

    def lookup(self, tokens) -> Tensor:
        """tokens: StringTensor/list of tokens -> int32 ids Tensor."""
        st = to_string_tensor(tokens)
        unk = self._id[self.unk_token]
        ids = np.asarray([self._id.get(s, unk) for s in st._data.ravel()],
                         np.int32).reshape(st._data.shape)
        return Tensor(ids)

    def to_tokens(self, ids) -> StringTensor:
        arr = np.asarray(ids._value if isinstance(ids, Tensor) else ids)
        flat = [self._tok[int(i)] for i in arr.ravel()]
        return StringTensor(np.asarray(flat, object).reshape(arr.shape))


def tokenize(x, vocab: Vocab, lowercase: bool = True,
             max_len: Optional[int] = None, pad_token: str = "[PAD]"):
    """Whitespace tokenize + vocab lookup: StringTensor [b] -> ids
    [b, max_len] int32 Tensor (the host half of the reference's
    to-device text pipeline)."""
    x = to_string_tensor(x)
    rows = []
    for s in x._data.ravel():
        toks = (s.lower() if lowercase else s).split()
        rows.append(toks)
    if max_len is None:
        max_len = max((len(r) for r in rows), default=0)
    pad_id = vocab._id.get(pad_token, vocab._id[vocab.unk_token])
    unk = vocab._id[vocab.unk_token]
    out = np.full((len(rows), max_len), pad_id, np.int32)
    for i, r in enumerate(rows):
        for j, t in enumerate(r[:max_len]):
            out[i, j] = vocab._id.get(t, unk)
    return Tensor(out)
