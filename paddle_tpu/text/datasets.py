"""paddle.text.datasets namespace (reference python/paddle/text/datasets/):
the dataset classes live in text/__init__ here; this module is the
reference import path."""
from . import (  # noqa: F401
    Conll05st,
    Imdb,
    Imikolov,
    Movielens,
    UCIHousing,
    WMT14,
    WMT16,
)

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16"]
