"""paddle.text analog (reference: python/paddle/text/ — datasets + viterbi).

viterbi_decode mirrors paddle.text.viterbi_decode (phi viterbi_decode
kernel): CRF max-sum decoding, implemented as a lax.scan so it compiles to
one XLA while-free program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..io.dataset import Dataset


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decoding.

    Args:
        potentials: emissions [batch, seq_len, num_tags].
        transition_params: [num_tags, num_tags] (with BOS/EOS rows/cols last
            two when include_bos_eos_tag, matching the reference convention).
        lengths: [batch] int actual lengths (default full).
    Returns:
        (scores [batch], paths [batch, seq_len]) best tag sequences.
    """
    em = potentials._value if isinstance(potentials, Tensor) else jnp.asarray(potentials)
    tr = (transition_params._value if isinstance(transition_params, Tensor)
          else jnp.asarray(transition_params))
    b, s, n = em.shape
    if lengths is None:
        lens = jnp.full((b,), s, jnp.int32)
    else:
        lens = (lengths._value if isinstance(lengths, Tensor)
                else jnp.asarray(lengths)).astype(jnp.int32)

    if include_bos_eos_tag:
        # last two tags are BOS, EOS (reference convention)
        bos, eos = n - 2, n - 1
        init = em[:, 0] + tr[bos][None, :]
    else:
        init = em[:, 0]

    def step(carry, t):
        alpha, history_unused = carry
        # alpha: [b, n] best score ending in tag j at prev step
        scores = alpha[:, :, None] + tr[None, :, :]  # [b, from, to]
        best_prev = jnp.argmax(scores, axis=1)  # [b, n]
        best_score = jnp.max(scores, axis=1) + em[:, t]
        # freeze past the sequence end
        active = (t < lens)[:, None]
        best_score = jnp.where(active, best_score, alpha)
        return (best_score, None), best_prev

    (alpha, _), history = jax.lax.scan(
        step, (init, None), jnp.arange(1, s))
    # history: [s-1, b, n] argmax backpointers

    if include_bos_eos_tag:
        alpha = alpha + tr[:, eos][None, :]

    last_tag = jnp.argmax(alpha, axis=-1)  # [b]
    scores = jnp.max(alpha, axis=-1)

    def backtrace(carry, ptrs_t):
        tag, t = carry
        prev = jnp.take_along_axis(ptrs_t, tag[:, None], axis=1)[:, 0]
        # only move back while within the sequence
        within = (t < lens)
        tag = jnp.where(within, prev, tag)
        return (tag, t - 1), tag

    (_, _), path_rev = jax.lax.scan(
        backtrace, (last_tag, jnp.full((), s - 1, jnp.int32)), history[::-1])
    paths = jnp.concatenate([path_rev[::-1].T, last_tag[:, None]], axis=1)  # [b, s]
    return Tensor(scores), Tensor(paths.astype(jnp.int64))


class Imdb(Dataset):
    """IMDB sentiment stand-in (reference: text/datasets/imdb.py) — synthetic
    but learnable: token distribution depends on the label."""

    def __init__(self, mode="train", vocab_size=2000, seq_len=64,
                 n_samples=500, seed=0, **kwargs):
        rng = np.random.RandomState(seed if mode == "train" else seed + 1)
        self.labels = rng.randint(0, 2, n_samples)
        docs = []
        for y in self.labels:
            base = rng.randint(0, vocab_size // 2, seq_len)
            if y == 1:
                base = base + vocab_size // 2
            docs.append(base)
        self.docs = np.stack(docs).astype(np.int64)
        self.vocab_size = vocab_size

    def __getitem__(self, idx):
        return self.docs[idx], int(self.labels[idx])

    def __len__(self):
        return len(self.docs)


class Conll05st(Dataset):
    """SRL tagging stand-in (reference: text/datasets/conll05.py)."""

    def __init__(self, mode="train", vocab_size=500, num_tags=10, seq_len=32,
                 n_samples=200, seed=0, **kwargs):
        rng = np.random.RandomState(seed if mode == "train" else seed + 1)
        self.words = rng.randint(0, vocab_size, (n_samples, seq_len)).astype(np.int64)
        self.tags = (self.words % num_tags).astype(np.int64)  # learnable mapping
        self.num_tags = num_tags

    def __getitem__(self, idx):
        return self.words[idx], self.tags[idx]

    def __len__(self):
        return len(self.words)


from . import strings  # noqa: E402
from .strings import (  # noqa: E402
    StringTensor,
    Vocab,
    tokenize,
)

__all__ = ["viterbi_decode", "Imdb", "Conll05st", "strings", "StringTensor",
           "Vocab", "tokenize"]
