"""paddle.text analog (reference: python/paddle/text/ — datasets + viterbi).

viterbi_decode mirrors paddle.text.viterbi_decode (phi viterbi_decode
kernel): CRF max-sum decoding, implemented as a lax.scan so it compiles to
one XLA while-free program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..io.dataset import Dataset


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decoding.

    Args:
        potentials: emissions [batch, seq_len, num_tags].
        transition_params: [num_tags, num_tags] (with BOS/EOS rows/cols last
            two when include_bos_eos_tag, matching the reference convention).
        lengths: [batch] int actual lengths (default full).
    Returns:
        (scores [batch], paths [batch, seq_len]) best tag sequences.
    """
    em = potentials._value if isinstance(potentials, Tensor) else jnp.asarray(potentials)
    tr = (transition_params._value if isinstance(transition_params, Tensor)
          else jnp.asarray(transition_params))
    b, s, n = em.shape
    if lengths is None:
        lens = jnp.full((b,), s, jnp.int32)
    else:
        lens = (lengths._value if isinstance(lengths, Tensor)
                else jnp.asarray(lengths)).astype(jnp.int32)

    if include_bos_eos_tag:
        # last two tags are BOS, EOS (reference convention)
        bos, eos = n - 2, n - 1
        init = em[:, 0] + tr[bos][None, :]
    else:
        init = em[:, 0]

    def step(carry, t):
        alpha, history_unused = carry
        # alpha: [b, n] best score ending in tag j at prev step
        scores = alpha[:, :, None] + tr[None, :, :]  # [b, from, to]
        best_prev = jnp.argmax(scores, axis=1)  # [b, n]
        best_score = jnp.max(scores, axis=1) + em[:, t]
        # freeze past the sequence end
        active = (t < lens)[:, None]
        best_score = jnp.where(active, best_score, alpha)
        return (best_score, None), best_prev

    (alpha, _), history = jax.lax.scan(
        step, (init, None), jnp.arange(1, s))
    # history: [s-1, b, n] argmax backpointers

    if include_bos_eos_tag:
        alpha = alpha + tr[:, eos][None, :]

    last_tag = jnp.argmax(alpha, axis=-1)  # [b]
    scores = jnp.max(alpha, axis=-1)

    def backtrace(carry, ptrs_t):
        tag, t = carry
        prev = jnp.take_along_axis(ptrs_t, tag[:, None], axis=1)[:, 0]
        # only move back while within the sequence
        within = (t < lens)
        tag = jnp.where(within, prev, tag)
        return (tag, t - 1), tag

    (_, _), path_rev = jax.lax.scan(
        backtrace, (last_tag, jnp.full((), s - 1, jnp.int32)), history[::-1])
    paths = jnp.concatenate([path_rev[::-1].T, last_tag[:, None]], axis=1)  # [b, s]
    return Tensor(scores), Tensor(paths.astype(jnp.int64))


class Imdb(Dataset):
    """IMDB sentiment stand-in (reference: text/datasets/imdb.py) — synthetic
    but learnable: token distribution depends on the label."""

    def __init__(self, mode="train", vocab_size=2000, seq_len=64,
                 n_samples=500, seed=0, **kwargs):
        rng = np.random.RandomState(seed if mode == "train" else seed + 1)
        self.labels = rng.randint(0, 2, n_samples)
        docs = []
        for y in self.labels:
            base = rng.randint(0, vocab_size // 2, seq_len)
            if y == 1:
                base = base + vocab_size // 2
            docs.append(base)
        self.docs = np.stack(docs).astype(np.int64)
        self.vocab_size = vocab_size

    def __getitem__(self, idx):
        return self.docs[idx], int(self.labels[idx])

    def __len__(self):
        return len(self.docs)


class Conll05st(Dataset):
    """SRL tagging stand-in (reference: text/datasets/conll05.py)."""

    def __init__(self, mode="train", vocab_size=500, num_tags=10, seq_len=32,
                 n_samples=200, seed=0, **kwargs):
        rng = np.random.RandomState(seed if mode == "train" else seed + 1)
        self.words = rng.randint(0, vocab_size, (n_samples, seq_len)).astype(np.int64)
        self.tags = (self.words % num_tags).astype(np.int64)  # learnable mapping
        self.num_tags = num_tags

    def __getitem__(self, idx):
        return self.words[idx], self.tags[idx]

    def __len__(self):
        return len(self.words)


from . import strings  # noqa: E402
from .strings import (  # noqa: E402
    StringTensor,
    Vocab,
    tokenize,
)

__all__ = ["viterbi_decode", "Imdb", "Conll05st", "strings", "StringTensor",
           "Vocab", "tokenize", "ViterbiDecoder", "Imikolov", "Movielens", "UCIHousing", "WMT14", "WMT16"]


class ViterbiDecoder:
    """Layer twin of viterbi_decode (reference text/viterbi_decode.py
    ViterbiDecoder): holds the transition matrix + tag convention."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


class UCIHousing(Dataset):
    """Boston housing regression (reference text/datasets/uci_housing.py):
    13 features -> price. Synthetic stand-in (no egress): linear ground
    truth + noise, learnable by design."""

    def __init__(self, data_file=None, mode="train", download=True):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = 404 if mode == "train" else 102
        x = rng.randn(n, 13).astype(np.float32)
        w = np.linspace(-2, 2, 13).astype(np.float32)
        y = x @ w + 3.0 + rng.randn(n).astype(np.float32) * 0.1
        self.data = [(x[i], np.asarray([y[i]], np.float32))
                     for i in range(n)]

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class Imikolov(Dataset):
    """PTB-style n-gram LM dataset (reference text/datasets/imikolov.py):
    yields n-gram tuples from a synthetic Zipf corpus."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True):
        rng = np.random.RandomState(3 if mode == "train" else 4)
        vocab = 2000
        corpus = rng.zipf(1.3, size=20000) % vocab
        self.word_idx = {i: i for i in range(vocab)}
        self.data = []
        if data_type.upper() == "NGRAM":
            for i in range(len(corpus) - window_size):
                self.data.append(tuple(
                    np.asarray(corpus[i + j], np.int64)
                    for j in range(window_size)))
        else:  # SEQ: (input seq, shifted target seq)
            seqlen = window_size
            for i in range(0, len(corpus) - seqlen - 1, seqlen):
                self.data.append((corpus[i:i + seqlen].astype(np.int64),
                                  corpus[i + 1:i + seqlen + 1]
                                  .astype(np.int64)))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """MovieLens-style rating dataset (reference
    text/datasets/movielens.py): (user feats, movie feats, rating)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        rng = np.random.RandomState(rand_seed)
        n_users, n_movies = 500, 800
        n = 8000
        users = rng.randint(0, n_users, n)
        movies = rng.randint(0, n_movies, n)
        u_bias = rng.randn(n_users) * 0.5
        m_bias = rng.randn(n_movies) * 0.5
        ratings = np.clip(np.round(
            3.0 + u_bias[users] + m_bias[movies] + rng.randn(n) * 0.3),
            1, 5)
        cut = int(n * (1 - test_ratio))
        sl = slice(0, cut) if mode == "train" else slice(cut, n)
        self.data = [
            (np.asarray([users[i], users[i] % 2, users[i] % 7,
                         users[i] % 21], np.int64),
             np.asarray([movies[i], movies[i] % 19], np.int64),
             np.asarray([ratings[i]], np.float32))
            for i in range(*sl.indices(n))]

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class _WMTBase(Dataset):
    """Synthetic translation pairs with a learnable copy-ish mapping:
    target = source permuted through a fixed bijection (BOS/EOS framed)."""

    def __init__(self, mode, src_dict_size, trg_dict_size, lang, seed):
        rng = np.random.RandomState(seed + (0 if mode in ("train",) else 1))
        self.src_vocab = min(src_dict_size, 1000) or 1000
        self.trg_vocab = min(trg_dict_size, 1000) or 1000
        perm = rng.permutation(self.trg_vocab)
        n = 2000 if mode == "train" else 400
        self.data = []
        for _ in range(n):
            ln = rng.randint(4, 12)
            src = rng.randint(3, self.src_vocab, ln)
            trg = perm[src % self.trg_vocab]
            self.data.append((src.astype(np.int64),
                              np.concatenate([[1], trg]).astype(np.int64),
                              np.concatenate([trg, [2]]).astype(np.int64)))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class WMT14(_WMTBase):
    """Reference text/datasets/wmt14.py (en-fr)."""

    def __init__(self, data_file=None, mode="train", dict_size=1000,
                 download=True):
        super().__init__(mode, dict_size, dict_size, "enfr", 10)


class WMT16(_WMTBase):
    """Reference text/datasets/wmt16.py (en-de, separate dict sizes)."""

    def __init__(self, data_file=None, mode="train", src_dict_size=1000,
                 trg_dict_size=1000, lang="en", download=True):
        super().__init__(mode, src_dict_size, trg_dict_size, lang, 20)

from . import datasets  # noqa: F401, E402  (reference import path)
