"""paddle.linalg namespace (reference python/paddle/linalg.py: 25 re-exports
from tensor.linalg). All but two ARE registered ops; `inv` is the registry's
`inverse`, and pca_lowrank composes center + svd here."""
from __future__ import annotations

from .ops.api import (  # noqa: F401
    cholesky,
    cholesky_solve,
    corrcoef,
    cov,
    det,
    eig,
    eigh,
    eigvals,
    eigvalsh,
    lstsq,
    matrix_power,
    matrix_rank,
    multi_dot,
    norm,
    pinv,
    qr,
    slogdet,
    solve,
    svd,
    triangular_solve,
)
from .ops.api import inverse as inv  # noqa: F401
from .ops.api import lu as _lu_op  # noqa: F401
from .ops.api import lu_unpack as _lu_unpack_op  # noqa: F401


def cond(x, p=None, name=None):
    """Matrix condition number (reference paddle.linalg.cond). NOTE: the
    registry's `cond` is the CONTROL-FLOW op (lax.cond) — re-exporting it
    here made the condition-number API unusable."""
    from .ops import api as _api

    if p in (None, 2, 2.0):
        s = svd(x, full_matrices=False)[1]
        return _api.divide(s[..., 0], s[..., -1])
    if p in (-2, -2.0):
        s = svd(x, full_matrices=False)[1]
        return _api.divide(s[..., -1], s[..., 0])
    if p in ("fro", "nuc", 1, -1, float("inf"), float("-inf")):
        nx = norm(x, p=p, axis=(-2, -1))
        ni = norm(inv(x), p=p, axis=(-2, -1))
        return _api.multiply(nx, ni)
    raise ValueError(f"unsupported p={p!r} for cond")


def lu(x, pivot=True, get_infos=False, name=None):
    """paddle.linalg.lu: pivots are 1-INDEXED in the reference contract;
    the kernel returns jax's 0-indexed pivots, converted here."""
    from .ops import api as _api

    lu_mat, piv = _lu_op(x)
    piv1 = _api.add(piv, _as_int32_one(piv))
    if get_infos:
        import jax.numpy as jnp

        from .core.tensor import Tensor

        info = Tensor(jnp.zeros(x.shape[:-2], jnp.int32))
        return lu_mat, piv1, info
    return lu_mat, piv1


def _as_int32_one(like):
    import jax.numpy as jnp

    from .core.tensor import Tensor

    return Tensor(jnp.ones((), jnp.int32))


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """paddle.linalg.lu_unpack consumes the 1-indexed pivots lu() above
    returns; the kernel expects 0-indexed."""
    from .ops import api as _api

    y0 = _api.subtract(y, _as_int32_one(y))
    return _lu_unpack_op(x, y0, unpack_ludata, unpack_pivots)

__all__ = [
    "cholesky", "norm", "cond", "cov", "corrcoef", "inv", "eig", "eigvals",
    "multi_dot", "matrix_rank", "svd", "qr", "pca_lowrank", "lu",
    "lu_unpack", "matrix_power", "det", "slogdet", "eigh", "eigvalsh",
    "pinv", "solve", "cholesky_solve", "triangular_solve", "lstsq",
]


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Principal components via truncated SVD (reference
    python/paddle/tensor/linalg.py pca_lowrank uses the randomized
    Halko-Martinsson-Tropp sketch for very wide matrices; at framework
    scale the exact thin SVD of the centered matrix is the TPU-friendly
    form — one jittable svd instead of niter QR passes)."""
    from .ops import api as _api

    m, n = int(x.shape[-2]), int(x.shape[-1])
    if q is None:
        q = min(6, m, n)
    if center:
        mean = _api.mean(x, axis=-2, keepdim=True)
        x = _api.subtract(x, mean)
    u, s, v = svd(x, full_matrices=False)
    # svd returns V^H rows; pca_lowrank returns V columns
    vt = _api.transpose(v, list(range(v.ndim - 2)) + [v.ndim - 1, v.ndim - 2])
    return u[..., :, :q], s[..., :q], vt[..., :, :q]
