"""paddle.linalg namespace (reference python/paddle/linalg.py: 25 re-exports
from tensor.linalg). All but two ARE registered ops; `inv` is the registry's
`inverse`, and pca_lowrank composes center + svd here."""
from __future__ import annotations

from .ops.api import (  # noqa: F401
    cholesky,
    cholesky_solve,
    cond,
    corrcoef,
    cov,
    det,
    eig,
    eigh,
    eigvals,
    eigvalsh,
    lstsq,
    lu,
    lu_unpack,
    matrix_power,
    matrix_rank,
    multi_dot,
    norm,
    pinv,
    qr,
    slogdet,
    solve,
    svd,
    triangular_solve,
)
from .ops.api import inverse as inv  # noqa: F401

__all__ = [
    "cholesky", "norm", "cond", "cov", "corrcoef", "inv", "eig", "eigvals",
    "multi_dot", "matrix_rank", "svd", "qr", "pca_lowrank", "lu",
    "lu_unpack", "matrix_power", "det", "slogdet", "eigh", "eigvalsh",
    "pinv", "solve", "cholesky_solve", "triangular_solve", "lstsq",
]


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Principal components via truncated SVD (reference
    python/paddle/tensor/linalg.py pca_lowrank uses the randomized
    Halko-Martinsson-Tropp sketch for very wide matrices; at framework
    scale the exact thin SVD of the centered matrix is the TPU-friendly
    form — one jittable svd instead of niter QR passes)."""
    from .ops import api as _api

    m, n = int(x.shape[-2]), int(x.shape[-1])
    if q is None:
        q = min(6, m, n)
    if center:
        mean = _api.mean(x, axis=-2, keepdim=True)
        x = _api.subtract(x, mean)
    u, s, v = svd(x, full_matrices=False)
    # svd returns V^H rows; pca_lowrank returns V columns
    vt = _api.transpose(v, list(range(v.ndim - 2)) + [v.ndim - 1, v.ndim - 2])
    return u[..., :, :q], s[..., :q], vt[..., :, :q]
