"""Public utilities: the custom-op extension point.

Reference: the C++ custom-op path — `paddle/phi/api/ext/op_meta_info.h:1`
(PD_BUILD_OP forward/backward registration) and
`python/paddle/utils/cpp_extension/` (load + setup build flow).

TPU-native redesign: a custom op is a pure function of jax arrays (optionally
a Pallas kernel). There is no C++ build step — registration drops the
function into the same registry every built-in op uses, so the op
automatically gets:
  * eager autograd (jax.vjp at dispatch, or the user's backward rule),
  * AMP casting hooks,
  * InferMeta (jax.eval_shape on the kernel),
  * static-mode Program recording and `paddle_tpu.jit.to_static` tracing,
  * the compiled-executable eager cache.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


def register_custom_op(
    forward: Callable = None,
    *,
    name: Optional[str] = None,
    backward: Optional[Callable] = None,
    amp: Optional[str] = None,
    cacheable: Optional[bool] = None,
):
    """Register a custom op into the paddle_tpu op registry + api namespace.

    forward(*arrays, **attrs) -> array | tuple — the kernel, written against
      jax arrays (jnp / lax / Pallas). Tensor arguments arrive unwrapped.
    backward(*inputs, *outputs, *grad_outputs, **attrs) -> grads — optional
      custom gradient (the reference PD_BUILD_GRAD_OP contract: backward sees
      the forward's inputs, outputs, and output cotangents). Return one grad
      per tensor input, None for non-differentiable ones. Omitted => autodiff
      of the forward (jax.vjp) is used, which is already correct for any
      jax-traceable kernel; a jax.custom_vjp-wrapped forward also works as-is.
    amp: None | 'white' | 'black' — AMP cast list membership.
    cacheable: set False for kernels that capture external state (e.g. the
      current device mesh) that is not part of their arguments.

    Returns the dispatching wrapper (also available as
    `paddle_tpu.ops.api.<name>`). Usable as a decorator::

        @register_custom_op(name="fused_thing", backward=fused_thing_grad)
        def fused_thing(x, w, *, eps=1e-5): ...
    """

    def deco(fwd_fn):
        from ..ops import api
        from ..ops.registry import register_op

        opname = name or fwd_fn.__name__
        if backward is None:
            kernel = fwd_fn
        else:
            @functools.lru_cache(maxsize=64)
            def _for_attrs(attr_key):
                attrs = dict(attr_key)

                def base(*args):
                    return fwd_fn(*args, **attrs)

                cv = jax.custom_vjp(base)

                def _fwd(*args):
                    out = base(*args)
                    return out, (args, out)

                def _bwd(res, g):
                    args, out = res
                    outs = out if isinstance(out, tuple) else (out,)
                    gs = tuple(g) if isinstance(g, (tuple, list)) else (g,)
                    grads = backward(*args, *outs, *gs, **attrs)
                    if not isinstance(grads, (tuple, list)):
                        grads = (grads,)
                    if len(grads) != len(args):
                        raise ValueError(
                            f"custom op {opname!r}: backward returned "
                            f"{len(grads)} grads for {len(args)} inputs")
                    return tuple(
                        jnp.zeros_like(a) if gr is None else gr
                        for a, gr in zip(args, grads))

                cv.defvjp(_fwd, _bwd)
                return cv

            def kernel(*args, **kwargs):
                try:
                    attr_key = tuple(sorted(kwargs.items()))
                    hash(attr_key)
                except TypeError:
                    raise TypeError(
                        f"custom op {opname!r}: attributes must be hashable "
                        "(they select the compiled gradient rule)") from None
                return _for_attrs(attr_key)(*args)

            functools.update_wrapper(kernel, fwd_fn)
        register_op(opname, kernel, amp=amp, cacheable=cacheable)
        return getattr(api, opname)

    if forward is not None:
        return deco(forward)
    return deco


def deprecated(update_to="", since="", reason="", level=0):
    """Decorator marking an API deprecated (reference
    utils/deprecated.py): warns once per call site."""
    import functools
    import warnings

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API {fn.__module__}.{fn.__name__} is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f"; use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return wrapper

    return deco


def run_check():
    """Install sanity check (reference utils/install_check.py run_check):
    one compiled matmul on the default backend + an 8-device CPU-mesh
    collective, printing the verdict."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle

    backend = jax.default_backend()
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    y = (x @ x).numpy()
    assert np.allclose(y, 4.0), "matmul check failed"
    print(f"paddle_tpu is installed successfully! backend={backend}, "
          f"devices={len(jax.devices())}")


def require_version(min_version, max_version=None):
    """Assert the framework version lies in [min_version, max_version]
    (reference utils/__init__.py require_version)."""
    import paddle_tpu

    def parse(v):
        return tuple(int(x) for x in str(v).split(".")[:3])

    cur = parse(paddle_tpu.__version__)
    if parse(min_version) > cur:
        raise Exception(
            f"version {paddle_tpu.__version__} < required {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"version {paddle_tpu.__version__} > allowed {max_version}")
    return True


def try_import(module_name, err_msg=None):
    """Import or raise with an actionable message (reference
    utils/lazy_import.py try_import)."""
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"{module_name} is required but not installed; this "
            "environment forbids pip installs — gate the feature") from e
