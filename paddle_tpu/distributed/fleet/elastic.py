"""Elastic training manager.

Reference: python/paddle/distributed/fleet/elastic/manager.py:124 — etcd node
registry under lease TTL (:251-264), membership watch, scale in/out within
[min_np, max_np], restart of training processes.

TPU-native: the registry lives in the native TCPStore (DCN-side host state;
SURVEY.md §5.3 calls for rendezvous + health on DCN with preemption-aware
restart). Nodes heartbeat `node/<rank>` keys; the manager detects stale
members, decides scale in/out, and signals the launcher (controller.py
elastic_level) to rebuild the pod. TPU preemption (maintenance events) shows
up as a vanished heartbeat exactly like a dead etcd lease.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional

from ... import native


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """Heartbeat registry + membership watcher over TCPStore."""

    def __init__(self, store=None, *, host: str = "127.0.0.1", port: int = 0,
                 rank: Optional[int] = None, np_range=(1, 1),
                 heartbeat_interval: float = 1.0, ttl: float = 5.0):
        self.rank = rank if rank is not None else int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        if store is None:
            if not native.available():
                raise RuntimeError("elastic needs the native TCPStore")
            store = native.TCPStore(host, port, is_master=(self.rank == 0))
        self.store = store
        self.min_np, self.max_np = np_range
        self.heartbeat_interval = heartbeat_interval
        self.ttl = ttl
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._watch_cbs: List[Callable[[Dict[int, float]], None]] = []

    # -- node registry (reference: manager.py:251 lease keepalive) ---------
    def register(self):
        self._beat()
        self._hb_thread = threading.Thread(target=self._hb_loop, daemon=True)
        self._hb_thread.start()

    def _beat(self):
        self.store.set(f"elastic/node/{self.rank}", str(time.time()))

    def _hb_loop(self):
        while not self._stop.is_set():
            self._beat()
            self._stop.wait(self.heartbeat_interval)

    def alive_nodes(self) -> Dict[int, float]:
        """Scan heartbeat keys; a node is alive if its beat VALUE changed
        within ttl by THIS host's clock. Comparing a remote wall-clock
        timestamp against the local clock would turn cross-host skew > ttl
        into false dead/alive verdicts; only the local observation time of
        a remote change is trustworthy."""
        now = time.time()
        if not hasattr(self, "_last_seen"):
            self._last_seen = {}  # rank -> (value, local time first seen)
        alive = {}
        for r in range(self.max_np):
            if self.store.get(f"elastic/exit/{r}", blocking=False) is not None:
                self._last_seen.pop(r, None)
                continue  # departed cleanly: not alive, not a failure
            v = self.store.get(f"elastic/node/{r}", blocking=False)
            if v is None:
                continue
            prev = self._last_seen.get(r)
            if prev is None or prev[0] != v:
                self._last_seen[r] = (v, now)
                alive[r] = now
            elif now - prev[1] <= self.ttl:
                alive[r] = prev[1]
        return alive

    def watch(self, expected_np: int) -> str:
        """One membership check (reference: manager.py watch:120).
        Cleanly-exited ranks shrink the expectation instead of reading as
        failures — a completed job must not restart forever."""
        exited = 0
        completed = 0
        for r in range(self.max_np):
            v = self.store.get(f"elastic/exit/{r}", blocking=False)
            if v is not None:
                exited += 1
                if v.decode() == ElasticStatus.COMPLETED:
                    completed += 1
        alive = self.alive_nodes()
        n = len(alive)
        for cb in self._watch_cbs:
            cb(alive)
        if exited and n == 0:
            return (ElasticStatus.COMPLETED
                    if completed == exited else ElasticStatus.ERROR)
        if n + exited == expected_np:
            return ElasticStatus.HOLD
        if n < self.min_np:
            return ElasticStatus.ERROR
        # scale-in (lost nodes but still viable) or scale-out (new nodes)
        return ElasticStatus.RESTART

    def add_watch_callback(self, cb: Callable[[Dict[int, float]], None]):
        self._watch_cbs.append(cb)

    def exit(self, completed: bool = True):
        self._stop.set()
        if self._hb_thread:
            self._hb_thread.join(timeout=2.0)
        self.store.set(f"elastic/exit/{self.rank}",
                       ElasticStatus.COMPLETED if completed else ElasticStatus.ERROR)
        try:  # drop the heartbeat so the departed rank never reads alive
            self.store.delete(f"elastic/node/{self.rank}")
        except Exception:
            pass
