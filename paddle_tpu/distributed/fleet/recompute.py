"""Activation recomputation (gradient/activation checkpointing).

Reference: python/paddle/distributed/fleet/recompute/recompute.py:334
(`recompute(function, *args)`) and recompute_sequential/recompute_hybrid.

TPU-native: the reference re-runs the forward inside a custom PyLayer backward
with saved RNG state. Here the whole segment becomes ONE vjp of a
`jax.checkpoint`-wrapped pure function, recorded as a single GradNode in the
eager grad graph. Under the jit executor (TrainStep) that lowers to true XLA
rematerialization — the backward pass recomputes the segment's activations
from its inputs instead of keeping them in HBM, trading MXU FLOPs for HBM
capacity. RNG consistency is structural: the trace-seed arithmetic is part of
the replayed computation, so dropout masks match between forward and
recompute (the reference saves/restores cuda RNG state by hand for the same
guarantee).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ...core import autograd as _ag
from ...core.autograd import GradNode
from ...core.tensor import Tensor
from ...nn.layer import Layer

__all__ = ["recompute", "recompute_sequential"]


_POLICIES = {
    None: None,
    "full": None,  # recompute everything (reference default)
    "dots_saveable": jax.checkpoint_policies.dots_saveable,
    "dots_with_no_batch_dims_saveable":
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
    "everything_saveable": jax.checkpoint_policies.everything_saveable,
}


def _is_tensor(x):
    return isinstance(x, Tensor)


def recompute(function: Callable, *args, policy: Optional[str] = None,
              preserve_rng_state: bool = True, use_reentrant: bool = True,
              **kwargs):
    """Run ``function(*args, **kwargs)`` without keeping its intermediate
    activations; they are recomputed during backward.

    When ``function`` is a Layer, its parameters participate in the grad graph
    (like the reference, where autograd tracks them through the replayed ops).
    ``policy`` selects what XLA may keep: None/'full' recomputes everything;
    'dots_saveable' keeps matmul outputs (jax.checkpoint_policies).
    """
    if policy not in _POLICIES:
        raise ValueError(f"unknown recompute policy {policy!r}; "
                         f"one of {sorted(k for k in _POLICIES if k)}")
    ckpt_policy = _POLICIES[policy]

    params = [p for p in function.parameters() if p.trainable] \
        if isinstance(function, Layer) else []

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs), is_leaf=_is_tensor)
    tensor_idx = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]
    arg_tensors = [leaves[i] for i in tensor_idx]

    grad_on = _ag.is_grad_enabled()
    primal_args = [
        k for k, t in enumerate(arg_tensors)
        if grad_on and not t.stop_gradient and jnp.issubdtype(t.dtype, jnp.inexact)
    ]
    primal_params = params if grad_on else []

    def run_with(arg_vals, param_vals):
        saved_p = [(p._value, p._grad_node, p.stop_gradient) for p in params]
        vals = list(leaves)
        for i, v in zip(tensor_idx, arg_vals):
            vals[i] = Tensor(v)
        a, k = jax.tree_util.tree_unflatten(treedef, vals)
        try:
            for p, v in zip(params, param_vals):
                p._value = v
                p._grad_node = None
                p.stop_gradient = True
            with _ag.no_grad():
                out = function(*a, **k)
        finally:
            for p, (v, gn, sg) in zip(params, saved_p):
                p._value, p._grad_node, p.stop_gradient = v, gn, sg
        out_leaves, out_treedef = jax.tree_util.tree_flatten(out, is_leaf=_is_tensor)
        out_vals = tuple(o._value if isinstance(o, Tensor) else o for o in out_leaves)
        return out_vals, out_treedef

    out_treedef_box = []

    if not (primal_args or primal_params):
        arg_vals = [t._value for t in arg_tensors]
        param_vals = [p._value for p in params]
        out_vals, out_treedef = run_with(arg_vals, param_vals)
        return jax.tree_util.tree_unflatten(
            out_treedef, [Tensor(v) for v in out_vals])

    primal_arg_set = set(primal_args)
    const_arg_vals = [t._value for k, t in enumerate(arg_tensors)
                      if k not in primal_arg_set]

    def pure(primal_arg_vals, param_vals):
        it_p = iter(primal_arg_vals)
        it_c = iter(const_arg_vals)
        arg_vals = [next(it_p) if k in primal_arg_set else next(it_c)
                    for k in range(len(arg_tensors))]
        out_vals, out_treedef = run_with(arg_vals, param_vals)
        if not out_treedef_box:
            out_treedef_box.append(out_treedef)
        return out_vals

    ckpt = jax.checkpoint(pure, policy=ckpt_policy)
    out_vals, vjp_fn = jax.vjp(
        ckpt,
        [arg_tensors[k]._value for k in primal_args],
        [p._value for p in primal_params],
    )
    out_treedef = out_treedef_box[0]

    # one GradNode covering the whole recomputed segment
    edges = []
    primal_tensors = [arg_tensors[k] for k in primal_args] + list(primal_params)
    for t in primal_tensors:
        if t._grad_node is not None:
            node, idx = t._grad_node
            edges.append(("node", node, idx))
        else:
            edges.append(("leaf", t))
    out_avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in out_vals]

    def segment_vjp(cots):
        cots = cots if isinstance(cots, tuple) else (cots,)
        d_args, d_params = vjp_fn(tuple(cots))
        return tuple(d_args) + tuple(d_params)

    node = GradNode("recompute", segment_vjp, edges, out_avals)

    wrapped = []
    for i, v in enumerate(out_vals):
        t = Tensor(v)
        if jnp.issubdtype(v.dtype, jnp.inexact):
            t.stop_gradient = False
            t._grad_node = (node, i)
        wrapped.append(t)
    return jax.tree_util.tree_unflatten(out_treedef, wrapped)


def recompute_sequential(ctx: Optional[dict], functions, *args, **kwargs):
    """Reference: recompute_sequential — run a Sequential/LayerList in
    `segments` chunks, each chunk recomputed."""
    ctx = ctx or {}
    segments = ctx.get("segments", 1)
    policy = ctx.get("policy", None)
    layers = list(functions)
    n = len(layers)
    per = (n + segments - 1) // segments
    out = args
    for s in range(0, n, per):
        chunk = layers[s:s + per]

        class _Chunk(Layer):
            def __init__(self, mods):
                super().__init__()
                from ...nn.container import LayerList

                self.mods = LayerList(mods)

            def forward(self, *xs):
                for m in self.mods:
                    xs = m(*xs) if isinstance(xs, tuple) else m(xs)
                    if not isinstance(xs, tuple):
                        xs = (xs,)
                return xs if len(xs) > 1 else xs[0]

        out = recompute(_Chunk(chunk), *(out if isinstance(out, tuple) else (out,)),
                        policy=policy, **kwargs)
        if not isinstance(out, tuple):
            out = (out,)
    return out if len(out) > 1 else out[0]
