"""Pipeline parallelism (reference: fleet/meta_parallel/pipeline_parallel.py:131
1F1B forward_backward_pipeline:382, pp_layers.py PipeLayer partitioning).

TPU-native round-1 implementation: GPipe-style microbatching where stages are
jit-compiled programs and stage handoff is a sharding annotation over the 'pp'
mesh axis (XLA inserts the device-to-device copies over ICI). The 1F1B
host-side schedule with donated activation buffers lands with the PP milestone
(SURVEY.md §7 M5); this class provides the reference's train_batch API shape.
"""
from __future__ import annotations

from ...core.tensor import Tensor
from ...nn.layer import Layer
from ...ops import api


class LayerDesc:
    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight", *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """Reference: parallel_layers/pp_layers.py PipeLayer — holds the full layer
    list plus a segmentation into stages."""

    def __init__(self, layers, num_stages=1, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, **kwargs):
        super().__init__()
        from ...nn.container import LayerList

        self._loss_fn = loss_fn
        self._num_stages = num_stages
        built = []
        for desc in layers:
            built.append(desc.build_layer() if isinstance(desc, LayerDesc) else desc)
        self.run_function = LayerList(built)
        # uniform segmentation (reference: segment by layer count)
        n = len(built)
        per = (n + num_stages - 1) // num_stages
        self._stage_bounds = [(i * per, min((i + 1) * per, n)) for i in range(num_stages)]

    def forward(self, x):
        for layer in self.run_function:
            x = layer(x)
        return x

    def get_stage_layers(self, stage_id):
        lo, hi = self._stage_bounds[stage_id]
        return list(self.run_function)[lo:hi]


class PipelineParallel(Layer):
    def __init__(self, layers, hcg, strategy):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)
        self._hcg = hcg
        pcfg = strategy.pipeline_configs if strategy else {}
        self.accumulate_steps = pcfg.get("accumulate_steps", 1)
        self.micro_batch_size = pcfg.get("micro_batch_size", 1)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Microbatched forward/backward with grad accumulation; stage-to-stage
        transfer is XLA's problem via the 'pp' sharding of layer params."""
        inputs, labels = data
        mb = self.accumulate_steps
        total = inputs.shape[0]
        step = max(total // mb, 1)
        losses = []
        for i in range(0, total, step):
            x = inputs[i : i + step]
            y = labels[i : i + step]
            out = self._layers(x)
            loss = self._layers._loss_fn(out, y) if hasattr(self._layers, "_loss_fn") and self._layers._loss_fn else out
            loss = loss / mb
            if scaler is not None:
                scaler.scale(loss).backward()
            else:
                loss.backward()
            losses.append(loss)
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return api.add_n([l.detach() for l in losses])
